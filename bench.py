"""Headline benchmark: RS(10,4) ec.encode throughput + 4-missing-shard rebuild p50.

Prints ONE JSON line:
    {"metric": "ec.encode", "value": <GB/s>, "unit": "GB/s/chip",
     "vs_baseline": <value / 8.0>, "rebuild": {...}, ...extras}

Baseline: BASELINE.md north stars — ≥8 GB/s/chip RS(10,4) encode on TPU v5e,
bit-identical to the Go/klauspost path (asserted against the C++ oracle before
timing), and 4-missing-shard rebuild p50 (the reference's `ec.rebuild`
worst case, `weed/storage/erasure_coding/ec_encoder.go:233`).

Method notes:
- Volume bytes are generated on-device: this terminal reaches its TPU through
  a tunnel whose host↔device link is ~100 MB/s (not representative of a real
  v5e host's PCIe). On-device generation isolates the encode kernel, which is
  the component this framework replaces (the klauspost SIMD Encode loop,
  `weed/storage/erasure_coding/ec_encoder.go:179`).
- Each config is probed in a fresh subprocess: the tunneled chip's free HBM
  varies (shared pool), and a RESOURCE_EXHAUSTED poisons the whole device
  session, so in-process retries always fail.
- Each probe runs 3 timed repetitions and reports the best: the shared chip
  shows occasional 4-5× slowdowns from co-tenant activity, and the best-of
  is the stable kernel rate (repeats agree within ~3% when the chip is quiet).
- All diagnostics go to stderr; stdout carries exactly one JSON line.
"""

import json
import os
import subprocess
import sys
import time

# retry-bind port plumbing shared with the chaos harnesses (util/netports):
# every subprocess-cluster probe allocates through one helper
from seaweedfs_tpu.util.netports import free_port  # noqa: E402


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def _timed_reps(run_once, reps: int = 3, iters: int = 6) -> list[float]:
    """Best-of-reps timing loop: returns per-rep seconds/iter."""
    out = []
    for _ in range(reps):
        t0 = time.perf_counter()
        run_once(iters)
        out.append((time.perf_counter() - t0) / iters)
    return out


def _sustained_rate(run_chain, bytes_per_iter: int, short: int = 32,
                    long_: int = 160, reps: int = 3) -> tuple[float, float]:
    """(sustained GB/s, raw long-chain GB/s).

    Chains of device ops measured at two lengths; the difference cancels the
    fixed chain overhead (jit dispatch ramp + ONE tunnel round-trip per
    chain, ~100 ms on this tunneled setup — a real v5e host pays ~10 µs).
    The r2 bench used 6-op chains, which buried the kernel under that fixed
    cost and reported 15.9 GB/s for a kernel actually sustaining ~75 GB/s.
    """
    def best(iters):
        times = []
        for _ in range(reps):
            t0 = time.perf_counter()
            run_chain(iters)
            times.append(time.perf_counter() - t0)
        return min(times)

    t_s = best(short)
    t_l = best(long_)
    sustained = bytes_per_iter * (long_ - short) / max(t_l - t_s, 1e-9) / 1e9
    raw = bytes_per_iter * long_ / t_l / 1e9
    return sustained, raw


# -- tile autotune sidecar -----------------------------------------------------
# The alt-geometry probes (RS(6,3)/RS(12,4)) historically swung ~50% between
# runs because every run RE-SWEPT tiles under a wall-clock guard: a slow host
# truncated the sweep at a different tile each time and published whatever it
# had. Warm-first protocol instead: the FIRST run sweeps (it is the warmup —
# its number is the sweep's best, and the winning tile is persisted to a JSON
# sidecar); every later run loads the pinned tile and measures ONLY it, so
# run-to-run spread is the kernel's own, not the tile lottery's.

def _tile_cache_path() -> str:
    """SWEED_TILE_CACHE > ~/.cache/sweed_tile.json > repo-local fallback
    (CI containers with read-only or absent home directories)."""
    env = os.environ.get("SWEED_TILE_CACHE")
    if env:
        return env
    cache_dir = os.path.expanduser("~/.cache")
    try:
        os.makedirs(cache_dir, exist_ok=True)
        probe = os.path.join(cache_dir, ".sweed_tile_probe")
        with open(probe, "w"):
            pass
        os.remove(probe)
        return os.path.join(cache_dir, "sweed_tile.json")
    except OSError:
        return os.path.join(
            os.path.dirname(os.path.abspath(__file__)), ".sweed_tile.json"
        )


def _tile_cache_load() -> dict:
    try:
        with open(_tile_cache_path()) as f:
            d = json.load(f)
        return d if isinstance(d, dict) else {}
    except (OSError, ValueError):
        return {}


def _tile_cache_store(key: str, entry: dict) -> None:
    path = _tile_cache_path()
    d = _tile_cache_load()
    d[key] = entry
    tmp = path + ".tmp"
    try:
        with open(tmp, "w") as f:
            json.dump(d, f, indent=1, sort_keys=True)
        os.replace(tmp, path)
    except OSError as e:  # cache is an optimization; the bench must not die
        log(f"tile cache write failed ({path}): {e}")


def probe_encode(chunk_mb: int, tile_kb: int) -> None:
    """Child mode: time encode for one config, print one float (GB/s)."""
    import jax
    import jax.numpy as jnp

    from seaweedfs_tpu.ec.codec import TpuCodec

    codec = TpuCodec(
        chunk_bytes=chunk_mb * 1024 * 1024, pallas_tile=tile_kb * 1024
    )
    n = chunk_mb * 1024 * 1024

    @jax.jit
    def checksum(x):
        return jnp.sum(x, dtype=jnp.uint32)

    # 4 distinct buffers cycled through the chain: rules out any
    # identical-request caching in the runtime/tunnel inflating the rate
    bufs = [
        jax.random.bits(jax.random.PRNGKey(i), (10, n), dtype=jnp.uint8)
        for i in range(4)
    ]
    for b in bufs:
        b.block_until_ready()
    _ = int(checksum(codec.matmul_device(codec.parity_rows, bufs[0])))  # warm

    def run(iters):
        acc = None
        for i in range(iters):
            s = checksum(codec.matmul_device(codec.parity_rows, bufs[i % 4]))
            acc = s if acc is None else acc + s
        _ = int(acc)  # forces execution of the whole chain

    sustained, raw = _sustained_rate(run, 10 * n)
    print(f"{sustained:.4f} {raw:.4f}")


def probe_rebuild(shard_mb: int, tile_kb: int) -> None:
    """Child mode: 4-missing-data-shard rebuild. Prints 'p50_s gbps'.

    Worst case of the reference's `ec.rebuild`: data shards 0-3 lost, rebuilt
    from the 10 remaining (6 data + 4 parity) via the inverted decode matrix
    (`ec_encoder.go:233` rebuildEcFiles → klauspost Reconstruct).
    """
    import jax
    import jax.numpy as jnp

    from seaweedfs_tpu.ec.codec import TpuCodec

    codec = TpuCodec(pallas_tile=tile_kb * 1024)
    n = shard_mb * 1024 * 1024
    present_rows = list(range(4, 14))  # shards 4..13 survive
    decode = codec._decode_matrix_for(present_rows)[:4]  # rows for shards 0-3

    @jax.jit
    def checksum(x):
        return jnp.sum(x, dtype=jnp.uint32)

    # generate in ≤32MB-wide pieces: threefry materialises ~8 bytes of
    # intermediates per output byte, so one (10, n) draw OOMs for big shards
    gen_w = 32 * 1024 * 1024
    pieces = [
        jax.random.bits(jax.random.PRNGKey(i), (10, min(gen_w, n - off)),
                        dtype=jnp.uint8)
        for i, off in enumerate(range(0, n, gen_w))
    ]
    # distinct chunk-width buffers for the sustained chain (kept BEFORE the
    # concatenate: device-side re-slicing would add copies the production
    # chunk-streaming rebuild never performs)
    cw = min(n, codec.chunk_bytes)
    chunk_bufs = [p for p in pieces if p.shape[1] == cw][:4]
    while len(chunk_bufs) < 4:  # small shards: keep the rotation distinct
        chunk_bufs.append(
            jax.random.bits(
                jax.random.PRNGKey(1000 + len(chunk_bufs)), (10, cw),
                dtype=jnp.uint8,
            )
        )
    present = pieces[0] if len(pieces) == 1 else jnp.concatenate(pieces, axis=1)
    del pieces
    present.block_until_ready()
    rebuilt = codec.matmul_device(decode, present)
    _ = int(checksum(rebuilt))  # compile + warm (full-shard chunked path)

    times = []
    for _ in range(9):
        t0 = time.perf_counter()
        rebuilt = codec.matmul_device(decode, present)
        _ = int(checksum(rebuilt))
        times.append(time.perf_counter() - t0)
    p50 = sorted(times)[len(times) // 2]
    del rebuilt, present  # free HBM headroom before queuing the chain

    # sustained KERNEL rate, same methodology and shape regime as encode's
    # probe: one chunk-width launch per iteration over rotated distinct
    # buffers, standard 32/160 chain lengths so the fixed per-chain sync
    # actually cancels (r4 ran 4-iteration deltas on big shards — most of
    # the 'rebuild 30% slower' gap was whole-shard slicing + concatenate
    # plus under-cancelled fixed cost, not the 4×10 matmul itself)
    _ = int(checksum(codec.matmul_device(decode, chunk_bufs[0])))  # warm shape

    def run(iters):
        acc = None
        for i in range(iters):
            s = checksum(codec.matmul_device(decode, chunk_bufs[i % len(chunk_bufs)]))
            acc = s if acc is None else acc + s
        _ = int(acc)

    sustained, _raw = _sustained_rate(run, 10 * cw)
    # GB/s of source bytes processed (10 shards in, 4 rebuilt out)
    print(f"{p50:.6f} {10 * n / p50 / 1e9:.4f} {sustained:.4f}")


def probe_mesh(chunk_mb: int, tile_kb: int) -> None:
    """Child mode: the MESH code path (MeshCodec.matmul_device) on a 1-device
    mesh (dp=sp=tp=1) on the real chip. With tp=1 the per-device body is the
    fused Pallas kernel under shard_map, so this certifies the multichip
    configuration inherits the single-chip rate (VERDICT r2 weak #3).
    Prints one float (GB/s)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from seaweedfs_tpu.ec.sharded import MeshCodec, build_mesh

    mesh = build_mesh(1)
    codec = MeshCodec(
        mesh=mesh, chunk_bytes=chunk_mb * 1024 * 1024,
        pallas_tile=tile_kb * 1024,
    )
    assert codec.use_pallas, "mesh probe must take the fused-kernel path"
    n = chunk_mb * 1024 * 1024

    @jax.jit
    def checksum(x):
        return jnp.sum(x, dtype=jnp.uint32)

    rng = np.random.default_rng(0)
    bufs = [
        codec.device_put(rng.integers(0, 256, (10, n), dtype=np.uint8))
        for _ in range(4)
    ]
    for b in bufs:
        b.block_until_ready()
    _ = int(checksum(codec.matmul_device(codec.parity_rows, bufs[0])))  # warm

    def run(iters):
        acc = None
        for i in range(iters):
            s = checksum(codec.matmul_device(codec.parity_rows, bufs[i % 4]))
            acc = s if acc is None else acc + s
        _ = int(acc)

    sustained, _raw = _sustained_rate(run, 10 * n)
    print(f"{sustained:.4f}")


def probe_rebuild_stream(shard_gb: int, chunk_mb: int) -> None:
    """Child mode: MEASURED 30GB-class rebuild via the chunked stream.

    A 30 GB volume has 3 GB shards (RS(10,4), ec_encoder.go:17-23); 10×3 GB
    of surviving shards don't fit HBM at once, so the production path
    (`rebuild_ec_files`, ec/encoder.py) streams column chunks. This probe
    executes that exact chunk loop on-device — shard_gb per shard in
    chunk_mb chunks, chained without per-chunk host sync — and reports the
    full-shard p50 over 3 runs. Replaces the linear extrapolation that
    BENCH_r02 carried (VERDICT r2 weak #2). Prints 'p50_s gbps n_chunks'."""
    import jax
    import jax.numpy as jnp

    from seaweedfs_tpu.ec.codec import TpuCodec

    codec = TpuCodec(pallas_tile=16 * 1024)
    chunk = chunk_mb * 1024 * 1024
    n_chunks = (shard_gb * 1024) // chunk_mb
    present_rows = list(range(4, 14))
    decode = codec._decode_matrix_for(present_rows)[:4]

    @jax.jit
    def checksum(x):
        return jnp.sum(x, dtype=jnp.uint32)

    bufs = [
        jax.random.bits(jax.random.PRNGKey(i), (10, chunk), dtype=jnp.uint8)
        for i in range(4)
    ]
    for b in bufs:
        b.block_until_ready()
    _ = int(checksum(codec.matmul_device(decode, bufs[0])))  # compile + warm

    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        acc = None
        for _c in range(n_chunks):
            s = checksum(codec.matmul_device(decode, bufs[_c % 4]))
            acc = s if acc is None else acc + s
        _ = int(acc)  # one host sync per full shard rebuild
        times.append(time.perf_counter() - t0)
    p50 = sorted(times)[len(times) // 2]
    total_bytes = 10 * chunk * n_chunks
    print(f"{p50:.4f} {total_bytes / p50 / 1e9:.4f} {n_chunks}")


def probe_smallfile(n: int, c: int) -> None:
    """Child mode: the reference's `weed benchmark` workload (1KB files)
    against an in-process master + volume server with the native turbo data
    plane. Prints one JSON line with req/s + p50 for both phases."""
    import tempfile

    import numpy as np

    from seaweedfs_tpu.__main__ import run_benchmark
    from seaweedfs_tpu.server.master_server import MasterServer
    from seaweedfs_tpu.server.volume_server import VolumeServer

    with tempfile.TemporaryDirectory() as tmp:
        ms = MasterServer(host="127.0.0.1", port=free_port()).start()
        vs = VolumeServer([tmp], host="127.0.0.1", port=free_port(),
                          master_url=ms.url).start()
        time.sleep(0.5)
        stats = run_benchmark(ms.url, n, c, 1024)
        out = {"turbo": vs.turbo is not None}
        for phase in ("write", "read"):
            lat = sorted(stats[phase]["latencies"])
            ok = len(lat)
            out[phase] = {
                "rps": round(ok / stats[phase]["wall"], 1),
                "p50_ms": round(lat[ok // 2] * 1e3, 2) if ok else None,
                "p99_ms": round(lat[int(ok * 0.99) - 1] * 1e3, 2) if ok else None,
                "failed": stats[phase]["failures"],
                "n": ok,
            }
        vs.stop()
        ms.stop()
    print(json.dumps(out))


def probe_filer_pipe(size_mb: int, window: int, chunk_mb: int = 4) -> None:
    """Child mode: large-file PUT/GET GB/s through the filer data plane at a
    given pipeline window (1 = the serial pre-pipeline behavior). Master,
    volume, and filer each run as a SEPARATE process — in one process the
    GIL serializes the very copy loops the pipeline overlaps and window=N
    measures nothing; the filer's chunk cache is disabled so every GET
    chunk is a real volume round-trip (what the read-ahead overlaps). The
    body is seeded random (incompressible — upload_data would gzip anything
    else and bench the compressor instead). Prints one JSON line with both
    rates and the GET body's sha256 so the parent can assert byte-identity
    across window settings."""
    import hashlib
    import io
    import socket
    import tempfile

    import numpy as np

    from seaweedfs_tpu.filer.client import FilerClient

    def wait_port(port, timeout=20.0):
        deadline = time.perf_counter() + timeout
        while time.perf_counter() < deadline:
            try:
                socket.create_connection(("127.0.0.1", port), 0.5).close()
                return
            except OSError:
                time.sleep(0.1)
        raise RuntimeError(f"server on :{port} never came up")

    def spawn(code, extra_env=None):
        env = dict(os.environ)
        if extra_env:
            env.update(extra_env)
        return subprocess.Popen(
            [sys.executable, "-c", code],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            cwd=os.path.dirname(os.path.abspath(__file__)), env=env,
        )

    n = size_mb * 1024 * 1024
    rng = np.random.default_rng(7)
    data = rng.integers(0, 256, n, dtype=np.uint8).tobytes()
    want_sha = hashlib.sha256(data).hexdigest()
    mp, fp = free_port(), free_port()
    # a single volume process saturates its own CPU and SERIALIZES under
    # concurrent access — a pipeline against one volume measures contention,
    # not overlap. Four volume processes are the deployment shape the
    # pipeline exists for: chunks spread across servers, window=N aggregates
    # their bandwidth
    vports = [free_port() for _ in range(4)]
    procs = []
    with tempfile.TemporaryDirectory() as tmp:
        try:
            procs.append(spawn(
                "import time\n"
                "from seaweedfs_tpu.server.master_server import MasterServer\n"
                f"MasterServer(host='127.0.0.1', port={mp}).start()\n"
                "time.sleep(3600)\n"
            ))
            wait_port(mp)
            # per-needle service delay in the volume children: on this
            # same-host (often single-core) bench rig every byte-copy is
            # CPU-serialized, so the only thing a pipeline can genuinely
            # overlap is WAITING — which is exactly what it overlaps in a
            # real deployment (cross-machine RTT + disk seek per chunk).
            # 25ms/needle ≈ a loaded HDD's random-access service time
            # (seek + rotational + queueing) plus the LAN round-trip.
            rtt_s = 0.025
            fault_env = {
                "SWEED_FAULTPOINTS": (
                    f"volume.read.needle=delay:{rtt_s}::0,"
                    f"volume.write.needle=delay:{rtt_s}::0"
                ),
                # the native turbo engine would serve fid GET/POST without
                # ever reaching the Python handlers that carry the delay
                # faultpoints — both window settings measure the same
                # instrumented path
                "SWEED_TURBO": "0",
            }
            for i, vp in enumerate(vports):
                vdir = os.path.join(tmp, f"v{i}")
                os.makedirs(vdir, exist_ok=True)
                procs.append(spawn(
                    "import time\n"
                    "from seaweedfs_tpu.server.volume_server import VolumeServer\n"
                    f"VolumeServer([{vdir!r}], host='127.0.0.1', port={vp}, "
                    f"master_url='127.0.0.1:{mp}').start()\n"
                    "time.sleep(3600)\n",
                    extra_env=fault_env,
                ))
            procs.append(spawn(
                "import time\n"
                "from seaweedfs_tpu.server.filer_server import FilerServer\n"
                f"FilerServer(host='127.0.0.1', port={fp}, "
                f"master_url='127.0.0.1:{mp}', "
                f"chunk_size={chunk_mb} * 1024 * 1024, chunk_cache_mem_mb=0, "
                f"read_window={window}, write_window={window}).start()\n"
                "time.sleep(3600)\n"
            ))
            for vp in vports:
                wait_port(vp)
            wait_port(fp)
            time.sleep(0.5)  # volume heartbeats → master topology
            client = FilerClient(f"127.0.0.1:{fp}")
            t0 = time.perf_counter()
            client.put_object_stream("/bench.bin", io.BytesIO(data), n)
            put_s = time.perf_counter() - t0
            get_s, got_sha = None, None
            for _ in range(2):  # second pass rides warm sockets; keep best
                pieces = []
                t0 = time.perf_counter()
                status, resp, _ = client.get_object_stream("/bench.bin")
                if status != 200:
                    raise RuntimeError(f"GET /bench.bin: HTTP {status}")
                if hasattr(resp, "read"):
                    while True:
                        piece = resp.read(1 << 20)
                        if not piece:
                            break
                        pieces.append(piece)
                    resp.close()
                else:
                    pieces.append(resp)
                dt = time.perf_counter() - t0  # hash OUTSIDE the timed
                # region — sha256 is ~the same order as the transfer
                # itself here and would mask the window's effect
                got_n = sum(len(p) for p in pieces)
                if got_n != n:
                    raise RuntimeError(f"GET length {got_n} != {n}")
                get_s = dt if get_s is None else min(get_s, dt)
                h = hashlib.sha256()
                for p in pieces:
                    h.update(p)
                got_sha = h.hexdigest()
        finally:
            for p in procs:
                p.terminate()
            for p in procs:
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    p.kill()
    print(json.dumps({
        "window": window,
        "size_mb": size_mb,
        "chunk_mb": chunk_mb,
        "modeled_rtt_ms": rtt_s * 1e3,
        "put_gbps": round(n / put_s / 1e9, 4),
        "get_gbps": round(n / get_s / 1e9, 4),
        "sha256": got_sha,
        "identical": got_sha == want_sha,
    }))


def probe_serving(mode: str, conns_csv: str, total: int) -> None:
    """Child mode: keep-alive smallfile GET storm against a filer running
    the given serving core (SWEED_SERVING=threads|aio). The filer runs in
    its own process; this process drives C concurrent keep-alive
    connections (asyncio client — holding 1k+ sockets is cheap on the
    load-generator side regardless of which core the SERVER uses) and
    sweeps C over `conns_csv`. Bodies are checked against the uploaded
    bytes on every response, so rps numbers only count verified replies.

    Two phases per connection count:
    - ``sat``   — closed loop, connection setup included: the storm
      arrives and the core must accept AND serve it. This is where
      thread-per-connection dies (a thread spawned per accept behind a
      5-deep listen backlog); rps is the capacity headline. p99 here is
      dominated by queueing (Little's law: C in flight / rps), so it is
      reported but NOT the latency verdict.
    - ``paced`` — open loop at a fixed offered rate (well under the
      64-conn capacity) over pre-opened, ramped connections: per-request
      latency now measures serving-core overhead at C connections, not
      saturation queueing. This is the p99-bounded-vs-64-conns verdict.

    Prints one JSON line:
    {"mode", "sweep": [{conns, sat: {...}, paced: {...}}],
     "serving_state": {native_hits, native_fallbacks, ...},
     "qos": {solo: {...}, contended: {...}, isolation_ok}}.

    ``serving_state`` is the served filer's /_status serving snapshot —
    in aio mode the native_hits counter is the evidence that the sweep
    actually exercised the native loop path, not the bridge.

    The ``qos`` phase runs against a SECOND filer started with a tenant
    governor budget (SWEED_QOS_RPS): a compliant tenant is paced solo,
    then again while a misbehaving tenant offers 10× its rate. Both
    per-tenant p99s come from the server's /metrics histogram quantiles
    (sweed_qos_request_seconds), shed counts from
    sweed_qos_decisions_total — the isolation verdict is assertable
    without log-greps."""
    import asyncio
    import math
    import re
    import socket
    import tempfile
    import urllib.request

    from seaweedfs_tpu.filer.client import FilerClient

    def wait_port(port, timeout=20.0):
        deadline = time.perf_counter() + timeout
        while time.perf_counter() < deadline:
            try:
                socket.create_connection(("127.0.0.1", port), 0.5).close()
                return
            except OSError:
                time.sleep(0.1)
        raise RuntimeError(f"server on :{port} never came up")

    def spawn(code, extra_env=None):
        env = dict(os.environ)
        if extra_env:
            env.update(extra_env)
        return subprocess.Popen(
            [sys.executable, "-c", code],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            cwd=os.path.dirname(os.path.abspath(__file__)), env=env,
        )

    mp, fp = free_port(), free_port()
    procs = []
    # the turbo engine would serve fid GETs natively on the VOLUME, but
    # the unit under test is the FILER's serving core; warm chunk cache
    # on the filer keeps volume round-trips out of the measured path so
    # the sweep isolates reactor-vs-thread-per-connection overhead
    serve_env = {"SWEED_SERVING": mode, "SWEED_TURBO": "0"}
    with tempfile.TemporaryDirectory() as tmp:
        try:
            procs.append(spawn(
                "import time\n"
                "from seaweedfs_tpu.server.master_server import MasterServer\n"
                f"MasterServer(host='127.0.0.1', port={mp}).start()\n"
                "time.sleep(3600)\n",
                extra_env=serve_env,
            ))
            wait_port(mp)
            vp = free_port()
            procs.append(spawn(
                "import time\n"
                "from seaweedfs_tpu.server.volume_server import VolumeServer\n"
                f"VolumeServer([{tmp!r}], host='127.0.0.1', port={vp}, "
                f"master_url='127.0.0.1:{mp}').start()\n"
                "time.sleep(3600)\n",
                extra_env=serve_env,
            ))
            procs.append(spawn(
                "import time\n"
                "from seaweedfs_tpu.server.filer_server import FilerServer\n"
                f"FilerServer(host='127.0.0.1', port={fp}, "
                f"master_url='127.0.0.1:{mp}').start()\n"
                "time.sleep(3600)\n",
                extra_env=serve_env,
            ))
            wait_port(vp)
            wait_port(fp)
            time.sleep(0.5)  # volume heartbeat → master topology
            client = FilerClient(f"127.0.0.1:{fp}")
            import numpy as np

            rng = np.random.default_rng(11)
            bodies = {}
            for i in range(64):
                data = rng.integers(0, 256, 1024, dtype=np.uint8).tobytes()
                client.put_object(f"/s/{i}", data)
                bodies[f"/s/{i}"] = data
            paths = sorted(bodies)
            for p in paths:  # warm the filer's chunk cache
                client.get_object(p)

            async def connect(counters, n_req, attempts=3):
                for attempt in range(attempts):  # ride out SYN-storm drops
                    try:
                        return await asyncio.wait_for(
                            asyncio.open_connection("127.0.0.1", fp),
                            timeout=10,
                        )
                    except (OSError, asyncio.TimeoutError):
                        await asyncio.sleep(0.2 * (attempt + 1))
                counters["failed"] += n_req
                return None, None

            async def pump(reader, writer, wid, n_req, counters,
                           latencies, interval, t_start):
                try:
                    for k in range(n_req):
                        if interval:
                            # absolute schedule (open loop): a slow reply
                            # must not thin the offered load behind it
                            due = t_start + k * interval
                            delay = due - time.perf_counter()
                            if delay > 0:
                                await asyncio.sleep(delay)
                        p = paths[(wid + k) % len(paths)]
                        req = (
                            f"GET {p} HTTP/1.1\r\nHost: b\r\n"
                            f"Content-Length: 0\r\n\r\n"
                        ).encode()
                        t0 = time.perf_counter()
                        try:
                            writer.write(req)
                            await writer.drain()
                            head = await asyncio.wait_for(
                                reader.readuntil(b"\r\n\r\n"), 60
                            )
                            status = int(head.split(b" ", 2)[1])
                            clen = 0
                            for ln in head.split(b"\r\n"):
                                if ln.lower().startswith(b"content-length:"):
                                    clen = int(ln.split(b":")[1])
                            body = await asyncio.wait_for(
                                reader.readexactly(clen), 60
                            )
                        except (OSError, asyncio.TimeoutError,
                                asyncio.IncompleteReadError,
                                asyncio.LimitOverrunError):
                            counters["failed"] += n_req - k
                            return  # connection is toast
                        latencies.append(time.perf_counter() - t0)
                        if status != 200 or body != bodies[p]:
                            counters["mismatched"] += 1
                finally:
                    writer.close()

            def summarize(c, latencies, counters, wall):
                lat = sorted(latencies)
                ok = len(lat)
                return {
                    "conns": c,
                    "n": ok,
                    "rps": round(ok / wall, 1) if wall > 0 else 0.0,
                    "p50_ms": round(lat[ok // 2] * 1e3, 2) if ok else None,
                    "p99_ms": round(
                        lat[max(0, int(ok * 0.99) - 1)] * 1e3, 2
                    ) if ok else None,
                    "failed": counters["failed"],
                    "mismatched": counters["mismatched"],
                }

            async def sat_phase(c, n_total):
                counters = {"failed": 0, "mismatched": 0}
                latencies = []
                per = [n_total // c + (1 if i < n_total % c else 0)
                       for i in range(c)]

                async def worker(wid, n_req):
                    reader, writer = await connect(counters, n_req)
                    if writer is None:
                        return
                    await pump(reader, writer, wid, n_req, counters,
                               latencies, 0.0, 0.0)

                t0 = time.perf_counter()
                await asyncio.gather(
                    *(worker(i, per[i]) for i in range(c) if per[i])
                )
                return summarize(
                    c, latencies, counters, time.perf_counter() - t0
                )

            async def paced_phase(c, n_total, target_rps):
                counters = {"failed": 0, "mismatched": 0}
                latencies = []
                per = [n_total // c + (1 if i < n_total % c else 0)
                       for i in range(c)]
                interval = c / target_rps  # per-connection request period
                ramp = min(5.0, max(0.5, c / 250.0))

                async def worker(wid, n_req):
                    # stagger connection setup so the listen backlog sees a
                    # trickle, then stagger request phases across the period
                    await asyncio.sleep(wid * ramp / c)
                    reader, writer = await connect(counters, n_req)
                    if writer is None:
                        return
                    t_start = (time.perf_counter() + ramp
                               + (wid % 97) / 97.0 * interval)
                    await pump(reader, writer, wid, n_req, counters,
                               latencies, interval, t_start)

                t0 = time.perf_counter()
                await asyncio.gather(
                    *(worker(i, per[i]) for i in range(c) if per[i])
                )
                # offered-load wall, net of ramp, so rps reflects the pace
                wall = max(time.perf_counter() - t0 - 2 * ramp, 1e-3)
                return summarize(c, latencies, counters, wall)

            out = {"mode": mode, "sweep": [], "paced_target_rps": 1200}
            for c in [int(x) for x in conns_csv.split(",") if x]:
                row = {"conns": c}
                row["sat"] = asyncio.run(sat_phase(c, total))
                row["paced"] = asyncio.run(paced_phase(
                    c, min(total, 6000), out["paced_target_rps"]
                ))
                out["sweep"].append(row)
            try:
                st = json.loads(urllib.request.urlopen(
                    f"http://127.0.0.1:{fp}/_status", timeout=10
                ).read())
                out["serving_state"] = st.get("serving", {})
            except Exception as e:  # noqa: BLE001 — evidence, not verdict
                out["serving_state"] = {"error": str(e)[:120]}

            # ---- per-tenant QoS isolation phase (second filer, governed)
            # budget well under the box's capacity knee (sat phase shows
            # ~2000 rps here): admission control pins the compliant
            # tenant's p99 only when the TOTAL admitted load leaves
            # headroom — a budget at the knee trades shed for queueing
            qp = free_port()
            qos_rps = 400
            procs.append(spawn(
                "import time\n"
                "from seaweedfs_tpu.server.filer_server import FilerServer\n"
                f"FilerServer(host='127.0.0.1', port={qp}, "
                f"master_url='127.0.0.1:{mp}').start()\n"
                "time.sleep(3600)\n",
                extra_env=dict(
                    serve_env,
                    SWEED_QOS_RPS=str(qos_rps),
                    SWEED_QOS_MAX_DELAY_MS="250",
                ),
            ))
            wait_port(qp)
            # the governed filer has its own (in-memory) metadata store:
            # re-publish the corpus there, then warm its chunk cache
            qclient = FilerClient(f"127.0.0.1:{qp}")
            for p in paths:
                qclient.put_object(p, bodies[p])
            for p in paths:
                st, got, _ = qclient.get_object(p)
                if st != 200 or got != bodies[p]:
                    raise RuntimeError(f"governed filer corpus bad: {p}")

            async def qos_worker(tenant, wid, interval, t_end, counters,
                                 lat):
                # shed replies close the connection (backpressure reaches
                # the abuser's socket), so the worker reconnects instead
                # of dying — the pacing schedule stays absolute
                reader = writer = None
                k = 0
                t_start = time.perf_counter() + (wid % 53) / 53.0 * interval
                while True:
                    due = t_start + k * interval
                    if due >= t_end:
                        break
                    delay = due - time.perf_counter()
                    if delay > 0:
                        await asyncio.sleep(delay)
                    k += 1
                    if writer is None:
                        try:
                            reader, writer = await asyncio.wait_for(
                                asyncio.open_connection("127.0.0.1", qp),
                                timeout=10,
                            )
                        except (OSError, asyncio.TimeoutError):
                            counters["failed"] += 1
                            continue
                    p = paths[(wid + k) % len(paths)]
                    req = (
                        f"GET {p} HTTP/1.1\r\nHost: b\r\n"
                        f"X-Sweed-Tenant: {tenant}\r\n"
                        f"Content-Length: 0\r\n\r\n"
                    ).encode()
                    t0 = time.perf_counter()
                    try:
                        writer.write(req)
                        await writer.drain()
                        head = await asyncio.wait_for(
                            reader.readuntil(b"\r\n\r\n"), 30
                        )
                        status = int(head.split(b" ", 2)[1])
                        clen, will_close = 0, False
                        for ln in head.split(b"\r\n"):
                            low = ln.lower()
                            if low.startswith(b"content-length:"):
                                clen = int(ln.split(b":")[1])
                            elif low.startswith(b"connection:") and (
                                b"close" in low
                            ):
                                will_close = True
                        body = await asyncio.wait_for(
                            reader.readexactly(clen), 30
                        )
                    except (OSError, asyncio.TimeoutError,
                            asyncio.IncompleteReadError,
                            asyncio.LimitOverrunError):
                        counters["failed"] += 1
                        writer.close()
                        reader = writer = None
                        continue
                    if status == 503:
                        counters["shed"] += 1
                    elif status == 200 and body == bodies[p]:
                        counters["ok"] += 1
                        lat.append(time.perf_counter() - t0)
                    else:
                        counters["mismatched"] += 1
                    if will_close:
                        writer.close()
                        reader = writer = None
                if writer is not None:
                    writer.close()

            async def qos_phase(tenants, secs):
                # tenants: (name, offered_rps, conns)
                res = {}
                tasks = []
                t_end = time.perf_counter() + secs
                for name, rps, nconn in tenants:
                    counters = {"ok": 0, "shed": 0, "failed": 0,
                                "mismatched": 0}
                    lat = []
                    res[name] = (counters, lat)
                    interval = nconn / rps
                    tasks.extend(
                        qos_worker(name, i, interval, t_end, counters, lat)
                        for i in range(nconn)
                    )
                await asyncio.gather(*tasks)
                out = {}
                for name, (counters, lat) in res.items():
                    lat.sort()
                    n = len(lat)
                    out[name] = dict(
                        counters,
                        client_p99_ms=round(
                            lat[max(0, int(n * 0.99) - 1)] * 1e3, 2
                        ) if n else None,
                    )
                return out

            def scrape_qos():
                text = urllib.request.urlopen(
                    f"http://127.0.0.1:{qp}/metrics", timeout=10
                ).read().decode()
                buckets: dict = {}
                for m in re.finditer(
                    r'sweed_qos_request_seconds_bucket\{([^}]*)\}\s+(\d+)',
                    text,
                ):
                    lab = dict(re.findall(r'(\w+)="([^"]*)"', m.group(1)))
                    le = lab.get("le", "")
                    edge = math.inf if le == "+Inf" else float(le)
                    buckets.setdefault(lab.get("tenant", ""), []).append(
                        (edge, int(m.group(2)))
                    )
                sheds: dict = {}
                delays: dict = {}
                for m in re.finditer(
                    r'sweed_qos_decisions_total\{([^}]*)\}\s+(\d+)', text
                ):
                    lab = dict(re.findall(r'(\w+)="([^"]*)"', m.group(1)))
                    if lab.get("outcome") == "shed":
                        sheds[lab.get("tenant", "")] = int(m.group(2))
                    elif lab.get("outcome") == "delay":
                        delays[lab.get("tenant", "")] = int(m.group(2))
                qt = {}
                for tenant, bs in buckets.items():
                    bs.sort()
                    total_n = bs[-1][1]
                    p99 = None
                    if total_n:
                        rank = 0.99 * total_n
                        prev_c, prev_e = 0, 0.0
                        for edge, cum in bs:
                            if cum >= rank:
                                span = cum - prev_c
                                e = edge if math.isfinite(edge) else prev_e
                                p99 = prev_e + (
                                    (e - prev_e) * (rank - prev_c) / span
                                    if span else 0.0
                                )
                                break
                            prev_c, prev_e = cum, (
                                edge if math.isfinite(edge) else prev_e
                            )
                    qt[tenant] = {
                        "count": total_n,
                        "p99_ms": round(p99 * 1e3, 2) if p99 is not None
                        else None,
                        "shed": sheds.get(tenant, 0),
                        "delayed": delays.get(tenant, 0),
                    }
                return qt

            # the compliant tenant stays strictly under its fair share
            # (150 < 400/2) so it never owes pacing delay; greedy needs
            # open-loop concurrency past max_delay × its share
            # (0.25s × 200rps = 50 in-flight) or pacing absorbs the whole
            # overage and shed never triggers
            solo = asyncio.run(qos_phase([("c-solo", 150, 8)], 6.0))
            contended = asyncio.run(qos_phase(
                [("c-load", 150, 8), ("greedy", 2000, 128)], 8.0
            ))
            server_view = scrape_qos()
            solo_p99 = server_view.get("hdr:c-solo", {}).get("p99_ms")
            cont_p99 = server_view.get("hdr:c-load", {}).get("p99_ms")
            out["qos"] = {
                "total_rps_budget": qos_rps,
                "solo": solo,
                "contended": contended,
                "server_metrics": server_view,
                "compliant_solo_p99_ms": solo_p99,
                "compliant_contended_p99_ms": cont_p99,
                "isolation_ok": bool(
                    solo_p99 and cont_p99 and cont_p99 <= 2.0 * solo_p99
                ),
                "greedy_shed": server_view.get("hdr:greedy", {}).get(
                    "shed", 0
                ),
                "greedy_delayed": server_view.get("hdr:greedy", {}).get(
                    "delayed", 0
                ),
            }
        finally:
            for p in procs:
                p.terminate()
            for p in procs:
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    p.kill()
    print(json.dumps(out))


def probe_trace(total: int = 8000, conns: int = 16) -> None:
    """Child mode: the tracing tax + the cluster-wide trace tree.

    Two three-daemon clusters (master+volume+filer, each its own process,
    SWEED_TURBO=0 so the measured path is the Python data plane the spans
    instrument): one with SWEED_TRACE=1, one with SWEED_TRACE=0. The same
    keep-alive smallfile GET storm runs against each (best of 3 reps);
    the rps delta is the always-on tracing overhead, budgeted at <=2%.

    With the traced cluster still up, one multi-chunk PUT and one GET are
    issued and their response trace ids walked back through every
    daemon's /debug/traces ring via the shell collector — the assembled
    tree (filer root → master assign → volume writes) is the acceptance
    artifact for end-to-end propagation across REAL process boundaries,
    not the in-process ring the unit tests see.

    Prints one JSON line:
    {"rps": {"traced", "untraced"}, "overhead_pct", "within_budget",
     "put_trace": {...}, "get_trace": {...}}
    """
    import asyncio
    import socket
    import tempfile

    from seaweedfs_tpu.filer.client import FilerClient

    def wait_port(port, timeout=20.0):
        deadline = time.perf_counter() + timeout
        while time.perf_counter() < deadline:
            try:
                socket.create_connection(("127.0.0.1", port), 0.5).close()
                return
            except OSError:
                time.sleep(0.1)
        raise RuntimeError(f"server on :{port} never came up")

    def spawn(code, extra_env):
        env = dict(os.environ)
        env.update(extra_env)
        return subprocess.Popen(
            [sys.executable, "-c", code],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            cwd=os.path.dirname(os.path.abspath(__file__)), env=env,
        )

    async def storm(fp, paths, bodies, c, n_total):
        """Closed-loop keep-alive GET storm; returns verified rps."""
        counters = {"failed": 0, "mismatched": 0}
        done = [0]

        async def worker(wid, n_req):
            try:
                reader, writer = await asyncio.wait_for(
                    asyncio.open_connection("127.0.0.1", fp), timeout=10
                )
            except (OSError, asyncio.TimeoutError):
                counters["failed"] += n_req
                return
            try:
                for k in range(n_req):
                    p = paths[(wid + k) % len(paths)]
                    writer.write(
                        (f"GET {p} HTTP/1.1\r\nHost: b\r\n"
                         f"Content-Length: 0\r\n\r\n").encode()
                    )
                    try:
                        await writer.drain()
                        head = await asyncio.wait_for(
                            reader.readuntil(b"\r\n\r\n"), 60
                        )
                        clen = 0
                        for ln in head.split(b"\r\n"):
                            if ln.lower().startswith(b"content-length:"):
                                clen = int(ln.split(b":")[1])
                        body = await asyncio.wait_for(
                            reader.readexactly(clen), 60
                        )
                    except (OSError, asyncio.TimeoutError,
                            asyncio.IncompleteReadError):
                        counters["failed"] += n_req - k
                        return
                    if body != bodies[p]:
                        counters["mismatched"] += 1
                    done[0] += 1
            finally:
                writer.close()

        per = [n_total // c + (1 if i < n_total % c else 0)
               for i in range(c)]
        t0 = time.perf_counter()
        await asyncio.gather(*(worker(i, per[i]) for i in range(c)
                               if per[i]))
        wall = max(time.perf_counter() - t0, 1e-3)
        return {
            "rps": round(done[0] / wall, 1),
            "failed": counters["failed"],
            "mismatched": counters["mismatched"],
        }

    def start_cluster(trace_on, tmp):
        serve_env = {
            "SWEED_SERVING": "threads",
            "SWEED_TURBO": "0",
            "SWEED_TRACE": "1" if trace_on else "0",
        }
        mp, vp, fp = free_port(), free_port(), free_port()
        procs = [spawn(
            "import time\n"
            "from seaweedfs_tpu.server.master_server import MasterServer\n"
            f"MasterServer(host='127.0.0.1', port={mp}).start()\n"
            "time.sleep(3600)\n",
            serve_env,
        )]
        wait_port(mp)
        procs.append(spawn(
            "import time\n"
            "from seaweedfs_tpu.server.volume_server import VolumeServer\n"
            f"VolumeServer([{tmp!r}], host='127.0.0.1', port={vp}, "
            f"master_url='127.0.0.1:{mp}').start()\n"
            "time.sleep(3600)\n",
            serve_env,
        ))
        procs.append(spawn(
            "import time\n"
            "from seaweedfs_tpu.server.filer_server import FilerServer\n"
            f"FilerServer(host='127.0.0.1', port={fp}, "
            f"master_url='127.0.0.1:{mp}').start()\n"
            "time.sleep(3600)\n",
            serve_env,
        ))
        wait_port(vp)
        wait_port(fp)
        time.sleep(0.5)  # volume heartbeat → master topology
        client = FilerClient(f"127.0.0.1:{fp}")
        import numpy as np

        rng = np.random.default_rng(13)
        bodies = {}
        for i in range(64):
            data = rng.integers(0, 256, 1024, dtype=np.uint8).tobytes()
            client.put_object(f"/t/{i}", data)
            bodies[f"/t/{i}"] = data
        paths = sorted(bodies)
        for p in paths:  # warm the filer chunk cache
            client.get_object(p)
        return procs, mp, fp, paths, bodies

    def stop(procs):
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()

    def _collect_probe_trees(mp, fp):
        from seaweedfs_tpu.server.http_util import http_bytes_headers
        from seaweedfs_tpu.shell.commands import CommandEnv, trace_collect

        env = CommandEnv(master=f"127.0.0.1:{mp}",
                         filer=f"127.0.0.1:{fp}")
        trees = {}
        blob = os.urandom(200_000)  # multi-chunk → assign + volume hops
        for key, (method, body) in (
            ("put_trace", ("POST", blob)),
            ("get_trace", ("GET", None)),
        ):
            st, _, hdrs = http_bytes_headers(
                method, f"http://127.0.0.1:{fp}/probe/trace.bin", body
            )
            tid = {k.lower(): v for k, v in hdrs.items()}.get(
                "x-sweed-trace-id", ""
            )
            time.sleep(0.3)  # streamed spans land after the reply
            report = trace_collect(env, tid) if tid else {}
            tree = report.get("tree", "")
            trees[key] = {
                "status": st,
                "trace_id": tid,
                "span_count": report.get("span_count", 0),
                "services": sorted({
                    ln.split()[0] for ln in tree.splitlines() if ln.strip()
                }),
                "tree": tree,
            }
        return trees

    # both clusters stay resident together and the storms alternate
    # between them: this host's run-to-run drift (shared CPU, frequency
    # scaling) is far larger than a 2% effect, and interleaving puts the
    # same drift on both sides of the subtraction
    import statistics

    with tempfile.TemporaryDirectory() as tmp_on, \
            tempfile.TemporaryDirectory() as tmp_off:
        procs_on = procs_off = None
        try:
            procs_on, mp_on, fp_on, paths_on, bodies_on = (
                start_cluster(True, tmp_on))
            procs_off, _, fp_off, paths_off, bodies_off = (
                start_cluster(False, tmp_off))
            reps_on, reps_off = [], []
            for _ in range(5):
                reps_on.append(asyncio.run(
                    storm(fp_on, paths_on, bodies_on, conns, total)))
                reps_off.append(asyncio.run(
                    storm(fp_off, paths_off, bodies_off, conns, total)))
            trees = _collect_probe_trees(mp_on, fp_on)
        finally:
            if procs_on:
                stop(procs_on)
            if procs_off:
                stop(procs_off)
    rps_on = round(statistics.median(r["rps"] for r in reps_on), 1)
    rps_off = round(statistics.median(r["rps"] for r in reps_off), 1)
    overhead = round((rps_off - rps_on) / max(rps_off, 1e-9) * 100.0, 2)
    print(json.dumps({
        "rps": {"traced": rps_on, "untraced": rps_off},
        "rps_reps": {"traced": [r["rps"] for r in reps_on],
                     "untraced": [r["rps"] for r in reps_off]},
        "failed": {"traced": sum(r["failed"] for r in reps_on),
                   "untraced": sum(r["failed"] for r in reps_off)},
        "mismatched": {"traced": sum(r["mismatched"] for r in reps_on),
                       "untraced": sum(r["mismatched"] for r in reps_off)},
        "overhead_pct": overhead,
        "within_budget": overhead <= 2.0,
        "put_trace": trees.get("put_trace"),
        "get_trace": trees.get("get_trace"),
    }))


def probe_hotshard(n_needles: int, n_requests: int) -> None:
    """Child mode: the hot-shard story end to end — zipfian (s≈1.1) GET
    storm against a prepopulated 2-node cluster, measured cold/random,
    after ``volume.balance -heat``, and after enabling the hot-needle RAM
    cache.  Every response body is byte-verified.

    Setup: ``n_needles`` needles are written directly into 8 volumes —
    the newest (hottest, the classic Haystack age skew) half of the
    corpus interleaves across volumes 5-8 and the cold half across 1-4.
    Volumes 1-4 start on node A and 5-8 on node B, so the zipf head
    concentrates on B but spans four volumes there: heat rebalance can
    genuinely split it (volume granularity could not split a single
    dominating volume — that case is the cache tier's job).  The
    volume servers run the aio core with the mmap needle-map kind and a
    modeled per-disk-read service delay (faultpoint, like the filer-pipe
    probe); a RAM cache hit skips the modeled seek exactly as it skips
    the real one.  Each GET storm is preceded by a small PUT storm
    through master ``/dir/assign`` so heat-weighted placement is on the
    measured path (the assign spread per node is reported).

    Phases: (A) baseline storm, cache off, heat accumulating;
    (B) ``volume.balance -heat -force`` moves hot replicas off node B via
    the existing copy path, then the same storm again; (C) cache enabled
    live via POST /admin/ncache on both servers, warmup pass, then the
    same storm.  Prints one JSON line with p50/p99 per phase, the
    balance plan, cache hit ratio, and the headline
    ``p99_improvement = baseline_p99 / after_cache_p99``."""
    import asyncio
    import socket
    import tempfile

    import numpy as np

    VOLS = 8
    PAYLOAD = 256
    READ_DELAY_S = 0.002  # modeled HDD seek per needle read (the Haystack
    # premise: one seek per read), serialized per node like one spindle —
    # load concentration queues, and RAM cache hits skip the line entirely
    ZIPF_S = 1.1
    CACHE_BYTES = 64 << 20
    conns = max(8, min(64, n_requests // 16))

    def payload_of(i: int) -> bytes:
        return (i.to_bytes(8, "big") * ((PAYLOAD + 7) // 8))[:PAYLOAD]

    def cookie_of(i: int) -> int:
        return (i * 0x9E3779B1 + 0x5EED) & 0xFFFFFFFF

    def vol_of(i: int) -> int:
        # newest half (the zipf head under rank = n-1-i) spreads over
        # volumes 5-8, oldest half over 1-4
        base = VOLS // 2 + 1 if i >= n_needles // 2 else 1
        return base + i % (VOLS // 2)

    def fid_of(i: int) -> str:
        from seaweedfs_tpu.storage.file_id import FileId

        return str(FileId(vol_of(i), i + 1, cookie_of(i)))

    def wait_port(port, timeout=30.0):
        deadline = time.perf_counter() + timeout
        while time.perf_counter() < deadline:
            try:
                socket.create_connection(("127.0.0.1", port), 0.5).close()
                return
            except OSError:
                time.sleep(0.1)
        raise RuntimeError(f"server on :{port} never came up")

    def spawn(code, extra_env=None):
        env = dict(os.environ)
        if extra_env:
            env.update(extra_env)
        return subprocess.Popen(
            [sys.executable, "-c", code],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            cwd=os.path.dirname(os.path.abspath(__file__)), env=env,
        )

    from seaweedfs_tpu.server.http_util import http_bytes, http_json
    from seaweedfs_tpu.storage.needle import Needle
    from seaweedfs_tpu.storage.replica_placement import ReplicaPlacement
    from seaweedfs_tpu.storage.volume import Volume

    mp = free_port()
    vports = [free_port(), free_port()]
    procs = []
    serve_env = {
        "SWEED_SERVING": "aio",
        "SWEED_TURBO": "0",  # heat accounting + faultpoints live in Python
        "SWEED_FAULTPOINTS": (
            f"volume.read.needle=serial-delay:{READ_DELAY_S}::0,"
            f"volume.write.needle=delay:{READ_DELAY_S}::0"
        ),
    }
    with tempfile.TemporaryDirectory() as tmp:
        # -- prepopulate: needles in index order; the newest (hottest)
        # half interleaves across vids 5-8 (node B), the cold half
        # across 1-4 (node A)
        dirs = [os.path.join(tmp, "v0"), os.path.join(tmp, "v1")]
        for d in dirs:
            os.makedirs(d)
        rp = ReplicaPlacement.from_string("000")
        vols = {
            vid: Volume(dirs[0] if vid <= VOLS // 2 else dirs[1], "", vid, rp)
            for vid in range(1, VOLS + 1)
        }
        for i in range(n_needles):
            vols[vol_of(i)].write_needle(
                Needle(cookie=cookie_of(i), id=i + 1, data=payload_of(i))
            )
        for v in vols.values():
            v.close()

        # -- zipf request schedule, shared by every phase (same offered
        # load, so the phases differ only in placement + cache)
        ranks = np.arange(1, n_needles + 1, dtype=np.float64)
        w = ranks ** -ZIPF_S
        rng = np.random.default_rng(7)
        sample = rng.choice(n_needles, size=n_requests, p=w / w.sum())
        idxs = (n_needles - 1 - sample).tolist()

        try:
            procs.append(spawn(
                "import time\n"
                "from seaweedfs_tpu.server.master_server import MasterServer\n"
                f"MasterServer(host='127.0.0.1', port={mp}).start()\n"
                "time.sleep(3600)\n",
                extra_env=serve_env,
            ))
            wait_port(mp)
            for d, vp in zip(dirs, vports):
                procs.append(spawn(
                    "import time\n"
                    "from seaweedfs_tpu.server.volume_server import VolumeServer\n"
                    f"VolumeServer([{d!r}], host='127.0.0.1', port={vp}, "
                    f"master_url='127.0.0.1:{mp}', max_volume_count=20, "
                    "pulse_seconds=0.5, needle_map_kind='mmap').start()\n"
                    "time.sleep(3600)\n",
                    extra_env=serve_env,
                ))
            for vp in vports:
                wait_port(vp)

            def locations() -> dict[int, str]:
                out = {}
                for vid in range(1, VOLS + 1):
                    r = http_json(
                        "GET",
                        f"http://127.0.0.1:{mp}/dir/lookup?volumeId={vid}",
                    )
                    locs = r.get("locations") or []
                    if locs:
                        out[vid] = locs[0]["url"]
                return out

            deadline = time.perf_counter() + 30
            vidurl = locations()
            while len(vidurl) < VOLS and time.perf_counter() < deadline:
                time.sleep(0.3)
                vidurl = locations()
            if len(vidurl) < VOLS:
                raise RuntimeError(f"only {len(vidurl)}/{VOLS} volumes registered")

            def put_storm(n_puts: int) -> dict:
                """Assign + upload through the master's heat-weighted pick;
                returns the per-node assign spread."""
                spread: dict[str, int] = {}
                blob = os.urandom(PAYLOAD)
                for _ in range(n_puts):
                    a = http_json("GET", f"http://127.0.0.1:{mp}/dir/assign")
                    url = a["url"]
                    spread[url] = spread.get(url, 0) + 1
                    st, _ = http_bytes(
                        "POST", f"http://{url}/{a['fid']}", blob
                    )
                    if st != 201:
                        raise RuntimeError(f"PUT {a['fid']}: HTTP {st}")
                return spread

            async def storm(vid2url: dict[int, str]) -> dict:
                counters = {"failed": 0, "mismatched": 0}
                latencies: list[float] = []
                per = [
                    n_requests // conns + (1 if k < n_requests % conns else 0)
                    for k in range(conns)
                ]

                async def worker(wid: int, count: int):
                    mine = idxs[wid::conns][:count]
                    pool: dict[str, tuple] = {}
                    try:
                        for i in mine:
                            url = vid2url[vol_of(i)]
                            rw = pool.get(url)
                            if rw is None:
                                hostp, portp = url.split(":")
                                rw = await asyncio.open_connection(
                                    hostp, int(portp)
                                )
                                pool[url] = rw
                            reader, writer = rw
                            req = (
                                f"GET /{fid_of(i)} HTTP/1.1\r\nHost: b\r\n"
                                "Content-Length: 0\r\n\r\n"
                            ).encode()
                            t0 = time.perf_counter()
                            try:
                                writer.write(req)
                                await writer.drain()
                                head = await asyncio.wait_for(
                                    reader.readuntil(b"\r\n\r\n"), 60
                                )
                                status = int(head.split(b" ", 2)[1])
                                clen = 0
                                for ln in head.split(b"\r\n"):
                                    if ln.lower().startswith(b"content-length:"):
                                        clen = int(ln.split(b":")[1])
                                body = await asyncio.wait_for(
                                    reader.readexactly(clen), 60
                                )
                            except (OSError, asyncio.TimeoutError,
                                    asyncio.IncompleteReadError,
                                    asyncio.LimitOverrunError):
                                counters["failed"] += 1
                                pool.pop(url, None)
                                continue
                            latencies.append(time.perf_counter() - t0)
                            if status != 200 or body != payload_of(i):
                                counters["mismatched"] += 1
                    finally:
                        for _, wtr in pool.values():
                            wtr.close()

                t0 = time.perf_counter()
                await asyncio.gather(
                    *(worker(k, per[k]) for k in range(conns) if per[k])
                )
                wall = time.perf_counter() - t0
                lat = sorted(latencies)
                ok = len(lat)
                return {
                    "n": ok,
                    "rps": round(ok / wall, 1) if wall > 0 else 0.0,
                    "p50_ms": round(lat[ok // 2] * 1e3, 2) if ok else None,
                    "p99_ms": round(
                        lat[max(0, int(ok * 0.99) - 1)] * 1e3, 2
                    ) if ok else None,
                    "failed": counters["failed"],
                    "mismatched": counters["mismatched"],
                }

            n_puts = max(10, n_requests // 20)
            out = {
                "needles": n_needles,
                "requests": n_requests,
                "zipf_s": ZIPF_S,
                "conns": conns,
                "modeled_read_ms": READ_DELAY_S * 1e3,
                "needle_map_kind": "mmap",
            }

            # -- phase A: cold/random baseline (heat accumulates here) ----
            out["assign_spread_baseline"] = put_storm(n_puts)
            out["baseline"] = asyncio.run(storm(vidurl))

            # -- phase B: heat-aware rebalance through the shell ----------
            from seaweedfs_tpu.shell import commands as C

            env = C.CommandEnv(f"127.0.0.1:{mp}")
            bal = C.volume_balance(env, apply=True, heat=True)
            out["balance_moved"] = bal["moved"]
            deadline = time.perf_counter() + 30
            vidurl = locations()
            while len(vidurl) < VOLS and time.perf_counter() < deadline:
                time.sleep(0.3)
                vidurl = locations()
            out["assign_spread_balanced"] = put_storm(n_puts)
            out["after_balance"] = asyncio.run(storm(vidurl))

            # -- phase C: hot-needle RAM cache on, warm, re-measure -------
            for vp in vports:
                http_json(
                    "POST",
                    f"http://127.0.0.1:{vp}/admin/ncache?capacity={CACHE_BYTES}",
                )
            asyncio.run(storm(vidurl))  # warmup: populates the cache
            out["after_cache"] = asyncio.run(storm(vidurl))
            ncache = {"hits": 0, "misses": 0}
            for vp in vports:
                s = http_json("GET", f"http://127.0.0.1:{vp}/status")
                ncache["hits"] += s["ncache"]["hits"]
                ncache["misses"] += s["ncache"]["misses"]
            lookups = ncache["hits"] + ncache["misses"]
            out["cache_hit_ratio"] = (
                round(ncache["hits"] / lookups, 4) if lookups else 0.0
            )
            base_p99 = out["baseline"]["p99_ms"]
            after_p99 = out["after_cache"]["p99_ms"]
            out["p99_improvement"] = (
                round(base_p99 / after_p99, 2)
                if base_p99 and after_p99 else None
            )
            out["mismatched"] = sum(
                out[ph]["mismatched"]
                for ph in ("baseline", "after_balance", "after_cache")
            )
        finally:
            for p in procs:
                p.terminate()
            for p in procs:
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    p.kill()
    print(json.dumps(out))


def probe_lifecycle(n_files: int = 64, n_requests: int = 4000) -> None:
    """Child mode: the lifecycle autopilot under LIVE zipf traffic with a
    drifting hot set, against a real in-process cluster (master + 2 volume
    servers, numpy EC fleet, fake-S3 cold tier).

    Phases: (seed) ``n_files`` files through ``/dir/assign`` across the
    auto-grown volumes; (quiesced) paced zipf GET storm over hot set A
    with the controller idle — baseline p50/p99; (live) the hot set
    DRIFTS to a disjoint volume group and the same storm runs while a
    ticker drives controller cycles every 0.5s, so set A cools and gets
    EC'd/tiered underneath live reads; (settle) trickle reads keep set B
    warm while cycles run until the plan goes quiet.  Every GET is
    byte-verified through every tier transition — a read racing an EC
    encode or an S3 upload must never return wrong bytes.

    Ends with the heat-tracking verdict: volumes the drift left cold must
    be EC'd or on the S3 tier, volumes in the live hot set must still be
    plain+local, and ``p99_ratio`` (live/quiesced) bounds the maintenance
    tax on tail latency.  Prints one JSON line."""
    import tempfile
    import threading

    import numpy as np

    ZIPF_S = 1.1
    HALFLIFE_S = 0.5
    HOT_VOLS = 3  # hot-set width, in volumes (drift = disjoint group)
    PAYLOAD_REPS = 512  # ~8KB per file

    # knobs must land before any seaweedfs_tpu import: the heat halflife
    # binds at stats.heat import time, the lifecycle config at master
    # construction
    os.environ["SWEED_HEAT_HALFLIFE"] = str(HALFLIFE_S)
    os.environ["SWEED_MESH"] = "1"
    os.environ["SWEED_LIFECYCLE_COLD_STREAK"] = "2"
    os.environ["SWEED_LIFECYCLE_MAX_ACTIONS"] = "8"
    os.environ["SWEED_LIFECYCLE_COOLDOWN"] = "3"
    os.environ["SWEED_LIFECYCLE_BUDGETS"] = (
        "ec=8,tier_up=4,tier_down=2,un_ec=2"
    )
    os.environ["SWEED_MAX_INFLIGHT"] = "10000"
    for k in ("SWEED_LIFECYCLE", "SWEED_FAULTPOINTS", "SWEED_SCRUB",
              "SWEED_TURBO", "SWEED_MESH_COORDINATOR", "NUM_PROCESSES",
              "PROCESS_ID", "SWEED_TIER_ENDPOINT"):
        os.environ.pop(k, None)

    import socket as _socket

    from seaweedfs_tpu.server.http_util import http_bytes, http_json
    from seaweedfs_tpu.storage.backend.fake_s3 import FakeS3Server

    def payload_of(i: int) -> bytes:
        return (b"lifecycle:%06d|" % i) * PAYLOAD_REPS

    with tempfile.TemporaryDirectory() as tmp:
        s3 = FakeS3Server(os.path.join(tmp, "s3")).start()
        os.environ["SWEED_TIER_ENDPOINT"] = s3.endpoint

        from seaweedfs_tpu.cluster.lifecycle import observe_topology
        from seaweedfs_tpu.server.master_server import MasterServer
        from seaweedfs_tpu.server.volume_server import VolumeServer

        master = MasterServer(
            port=free_port(), node_timeout=60,
            meta_dir=os.path.join(tmp, "meta"),
        ).start()
        vols = [
            VolumeServer(
                [os.path.join(tmp, f"v{k}")], port=free_port(),
                master_url=master.url, max_volume_count=30,
                pulse_seconds=0.3, ec_backend="numpy",
            ).start()
            for k in range(2)
        ]
        vurls = [f"{v.host}:{v.port}" for v in vols]
        try:
            # volume servers must be fleet members before fleet EC works
            deadline = time.time() + 30
            while True:
                st = http_json(
                    "GET", f"http://{master.url}/ec/fleet/status"
                )
                if len(st.get("members", [])) >= 2:
                    break
                if time.time() > deadline:
                    raise RuntimeError("fleet members never registered")
                time.sleep(0.2)

            # -- seed -----------------------------------------------------
            by_vid: dict[int, list] = {}
            for i in range(n_files):
                a = http_json("GET", f"http://{master.url}/dir/assign")
                body = payload_of(i)
                st, _ = http_bytes("POST", f"http://{a['url']}/{a['fid']}",
                                   body)
                if st != 201:
                    raise RuntimeError(f"seed PUT {a['fid']}: HTTP {st}")
                by_vid.setdefault(int(a["fid"].split(",")[0]), []).append(
                    (a["fid"], body)
                )
            seeded = sorted(by_vid)
            if len(seeded) < 2 * HOT_VOLS:
                raise RuntimeError(
                    f"only {len(seeded)} volumes seeded; need "
                    f"{2 * HOT_VOLS} for a disjoint drift"
                )
            set_a, set_b = seeded[:HOT_VOLS], seeded[HOT_VOLS:2 * HOT_VOLS]

            def zipf_requests(hot_vids, n):
                """Zipf-weighted (fid, body) schedule over the hot set's
                files, rank-ordered by volume so heat concentrates."""
                files = [f for v in hot_vids for f in by_vid[v]]
                ranks = np.arange(1, len(files) + 1, dtype=np.float64)
                w = ranks ** -ZIPF_S
                rng = np.random.default_rng(11)
                picks = rng.choice(len(files), size=n, p=w / w.sum())
                return [files[j] for j in picks]

            def read_one(fid, body):
                """Volume may be plain, mid-EC, EC, or on the S3 tier —
                try both servers; correctness bar is byte equality."""
                t0 = time.perf_counter()
                for url in vurls:
                    try:
                        st, data = http_bytes("GET", f"http://{url}/{fid}")
                    except OSError:
                        continue
                    if st == 200:
                        return time.perf_counter() - t0, data == body
                return time.perf_counter() - t0, None

            def storm(reqs, duration_s):
                lats, failed, mismatched = [], 0, 0
                t_start = time.perf_counter()
                pace = duration_s / max(1, len(reqs))
                for k, (fid, body) in enumerate(reqs):
                    tgt = t_start + k * pace
                    now = time.perf_counter()
                    if tgt > now:
                        time.sleep(tgt - now)
                    lat, ok = read_one(fid, body)
                    if ok is None:
                        failed += 1
                    elif not ok:
                        mismatched += 1
                    else:
                        lats.append(lat)
                lat = sorted(lats)
                n = len(lat)
                wall = time.perf_counter() - t_start
                return {
                    "n": n,
                    "rps": round(n / wall, 1) if wall > 0 else 0.0,
                    "p50_ms": round(lat[n // 2] * 1e3, 2) if n else None,
                    "p99_ms": round(
                        lat[max(0, int(n * 0.99) - 1)] * 1e3, 2
                    ) if n else None,
                    "failed": failed,
                    "mismatched": mismatched,
                }

            lc = master.lifecycle

            # -- quiesced baseline: hot set A, controller idle ------------
            quiesced = storm(zipf_requests(set_a, n_requests // 2), 6.0)

            # -- live: hot set drifts to B while cycles run.  A trickle
            # thread reads one file from EACH set-B volume continuously so
            # the live hot set stays observably warm across slow cycles
            # (a tier upload can outlast several heat halflives) — without
            # it the autopilot correctly tiers B too and the "tracks heat"
            # verdict has nothing to distinguish.
            stop_probe = threading.Event()
            summaries = []
            trickle_counts = {"failed": 0}

            def ticker():
                while not stop_probe.is_set():
                    try:
                        summaries.append(lc.tick())
                    except Exception as e:  # keep measuring through a bad cycle
                        log(f"lifecycle tick error: {e}")
                    stop_probe.wait(0.6)

            def trickler():
                while not stop_probe.is_set():
                    for v in set_b:
                        fid, body = by_vid[v][0]
                        _, ok = read_one(fid, body)
                        if ok is not True:
                            trickle_counts["failed"] += 1
                    stop_probe.wait(0.15)

            tick_thread = threading.Thread(target=ticker, daemon=True)
            trickle_thread = threading.Thread(target=trickler, daemon=True)
            trickle_thread.start()
            tick_thread.start()
            live = storm(zipf_requests(set_b, n_requests // 2), 12.0)

            # -- settle: cycles keep running until the plan goes quiet ----
            settle_deadline = time.time() + 60
            while time.time() < settle_deadline:
                tail = summaries[-3:]
                if len(tail) == 3 and not any(
                    s["actions"] or s["deferred"] for s in tail
                ):
                    break
                time.sleep(0.5)

            # -- verdict: does the tier distribution track the heat? ------
            time.sleep(0.8)  # one heartbeat so the observation is fresh
            obs = observe_topology(master)
            stop_probe.set()
            tick_thread.join(timeout=30)
            trickle_thread.join(timeout=10)
            settle_failed = trickle_counts["failed"]
            end_state = {}
            for vid in sorted(obs):
                ob = obs[vid]
                state = ("tiered" if ob["tiered"]
                         else "ec" if ob["kind"] == "ec" else "plain")
                end_state[str(vid)] = {
                    "heat": round(ob["heat"], 4),
                    "band": ob["band"],
                    "state": state,
                    "seeded": vid in by_vid,
                }
            moved_cold = [
                v for v in seeded if v not in set_b
                and end_state[str(v)]["state"] != "plain"
            ]
            hot_local = [
                v for v in set_b if end_state[str(v)]["state"] == "plain"
            ]
            cold_total = [v for v in seeded if v not in set_b]
            st = lc.status()
            out = {
                "files": n_files,
                "requests": n_requests,
                "volumes_seeded": len(seeded),
                "zipf_s": ZIPF_S,
                "heat_halflife_s": HALFLIFE_S,
                "hot_set_before": set_a,
                "hot_set_after": set_b,
                "quiesced": quiesced,
                "live": live,
                "p99_ratio": (
                    round(live["p99_ms"] / quiesced["p99_ms"], 2)
                    if live["p99_ms"] and quiesced["p99_ms"] else None
                ),
                "end_state": end_state,
                "tracking": {
                    "cold_moved": len(moved_cold),
                    "cold_total": len(cold_total),
                    "hot_still_local": len(hot_local),
                    "hot_total": len(set_b),
                    "fraction": round(
                        (len(moved_cold) + len(hot_local))
                        / max(1, len(cold_total) + len(set_b)), 3
                    ),
                },
                "tier": {
                    "s3_bytes": s3.bytes_stored(),
                    "tiered_vids": [
                        int(v) for v, e in end_state.items()
                        if e["state"] == "tiered"
                    ],
                    "ec_vids": [
                        int(v) for v, e in end_state.items()
                        if e["state"] == "ec"
                    ],
                },
                "actions": {
                    k: st["counters"][k]
                    for k in ("cycles", "actions_done", "actions_failed",
                              "actions_deferred", "cycles_deferred")
                },
                "failed": quiesced["failed"] + live["failed"] + settle_failed,
                "mismatched": quiesced["mismatched"] + live["mismatched"],
            }
        finally:
            for v in vols:
                v.stop()
            master.stop()
            s3.stop()
    print(json.dumps(out))


def probe_sync(n_files: int = 120, outage_s: float = 6.0) -> None:
    """Child mode: the active-active replication story end to end — a
    paced write storm against filer A with a live ReplicationController
    mirroring into filer B (steady-state lag sampled from the sync
    stats), then a full B-side outage under continued writes and the
    time for the pair to reconverge (full-tree content hash) once B
    returns. Also checks the `sync` section is exposed in `/_status` on
    both filers and that the DLQ ends empty. Prints one JSON line."""
    import hashlib
    import socket
    import tempfile

    from seaweedfs_tpu.filer.client import FilerClient
    from seaweedfs_tpu.replication import ReplicationController, sync_stats
    from seaweedfs_tpu.server.filer_server import FilerServer
    from seaweedfs_tpu.server.master_server import MasterServer
    from seaweedfs_tpu.server.volume_server import VolumeServer

    def tree(url):
        fc = FilerClient(url)
        out, stack = {}, ["/sync/"]
        while stack:
            d = stack.pop()
            for e in fc.list(d, limit=10_000):
                p = d + e["name"]
                if e.get("is_directory"):
                    stack.append(p + "/")
                else:
                    _, body, _ = fc.get_object(p)
                    out[p] = hashlib.sha1(body).hexdigest()
        return out

    def converge(budget_s, poll=0.25):
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < budget_s:
            try:
                if tree(fa.url) == tree(fb[0].url):
                    return round(time.perf_counter() - t0, 2)
            except OSError:
                pass
            time.sleep(poll)
        return None

    out = {"files": n_files, "outage_s": outage_s}
    with tempfile.TemporaryDirectory() as tmp:
        servers = []

        def mk(name):
            ms = MasterServer(host="127.0.0.1", port=free_port()).start()
            vs = VolumeServer(
                [os.path.join(tmp, f"vol_{name}")], host="127.0.0.1",
                port=free_port(), master_url=ms.url, pulse_seconds=0.3,
                max_volume_count=20,
            ).start()
            os.makedirs(os.path.join(tmp, f"vol_{name}"), exist_ok=True)
            f = FilerServer(
                host="127.0.0.1", port=free_port(), master_url=ms.url,
                chunk_size=256 * 1024,
                db_path=os.path.join(tmp, f"filer_{name}.db"),
            ).start()
            servers.extend([ms, vs, f])
            return ms, vs, f

        ma, va, fa = mk("a")
        mb, vb, fb_f = mk("b")
        fb = [fb_f]  # boxed: replaced across the outage restart
        time.sleep(0.7)
        ca = FilerClient(fa.url)
        ctrl = ReplicationController(
            fa.url, fb[0].url, dlq_dir=tmp, source_path="/sync",
            poll_interval=0.1,
        ).start()
        try:
            # -- steady state: paced storm, lag sampled mid-flight --------
            body = os.urandom(2048)
            lag_samples = []
            t0 = time.perf_counter()
            for i in range(n_files):
                ca.put_object(f"/sync/f{i:04d}.bin", body + str(i).encode())
                if i % 5 == 4:
                    lag_samples.append(
                        sync_stats()["totals"]["max_lag_s"]
                    )
                time.sleep(0.01)
            storm_s = time.perf_counter() - t0
            steady = converge(60)
            lag_samples.sort()
            out["steady"] = {
                "write_rps": round(n_files / storm_s, 1),
                "lag_p50_s": lag_samples[len(lag_samples) // 2],
                "lag_max_s": lag_samples[-1],
                "converge_after_storm_s": steady,
            }

            # -- `/_status` exposes the sync section on both filers -------
            from seaweedfs_tpu.server.http_util import http_json

            out["status_sync_sections"] = {
                name: sorted(
                    http_json("GET", f"http://{f.url}/_status")
                    .get("sync", {}).get("directions", {})
                )
                for name, f in (("a", fa), ("b", fb[0]))
            }

            # -- datacenter loss: B down, writes continue against A -------
            fb[0].stop()
            for i in range(n_files // 2):
                ca.put_object(f"/sync/o{i:04d}.bin", body + b"o%d" % i)
            time.sleep(outage_s)
            fb[0] = FilerServer(
                host="127.0.0.1", port=fb[0].port, master_url=mb.url,
                chunk_size=256 * 1024,
                db_path=os.path.join(tmp, "filer_b.db"),
            ).start()
            servers.append(fb[0])
            out["time_to_converge_s"] = converge(120)

            totals = sync_stats()["totals"]
            out["totals"] = {
                k: totals[k]
                for k in ("replicated", "redelivered", "retries",
                          "parked", "dlq_depth", "stalls")
            }
        finally:
            ctrl.stop()
            for s in reversed(servers):
                try:
                    s.stop()
                except Exception:
                    pass
    print(json.dumps(out))


def probe_meta(n_files: int = 480, c: int = 16) -> None:
    """Child mode: metadata-plane scale-out — the same create/lookup storm
    against a 1-filer and a 4-filer fleet. Each filer is a SEPARATE process
    over its own sqlite store (in one process the GIL serializes the very
    stores the ring spreads load across); `ring_peers` wires the 4-fleet
    into a ring. A 3ms delay faultpoint armed INSIDE the filer's
    create_entry lock models a loaded metadata store — the serialization
    point sharding exists to scale past; both fleet sizes run the same
    instrumented path. Workers pull shuffled paths off one shared queue so
    load spreads over the fleet the way real traffic does, instead of
    pinning each thread to a shard. After the storm the tree must read
    identically through every gateway shape: the smart ring client, a dumb
    307-following client aimed at EVERY member (spine listings fan out
    server-side), and the S3 gateway. Prints one JSON line with creates/s
    + lookups/s per fleet size and the scaling factor."""
    import concurrent.futures
    import queue
    import random
    import socket
    import tempfile
    import urllib.request

    from seaweedfs_tpu.filer.client import FilerClient
    from seaweedfs_tpu.filer.ring import RingFilerClient

    def wait_port(port, timeout=20.0):
        deadline = time.perf_counter() + timeout
        while time.perf_counter() < deadline:
            try:
                socket.create_connection(("127.0.0.1", port), 0.5).close()
                return
            except OSError:
                time.sleep(0.1)
        raise RuntimeError(f"server on :{port} never came up")

    def spawn(code, extra_env=None):
        env = dict(os.environ)
        if extra_env:
            env.update(extra_env)
        return subprocess.Popen(
            [sys.executable, "-c", code],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            cwd=os.path.dirname(os.path.abspath(__file__)), env=env,
        )

    # modeled store latency per create, held under the filer metadata lock
    # (the real serialization point): same method as the filer-pipe probe's
    # modeled needle RTT. On this often single-core bench rig every
    # python/sqlite instruction is CPU-serialized across the whole fleet,
    # so the modeled wait must DOMINATE the ~3ms real per-op cost — 20ms
    # (a loaded metadata store's commit: fsync + WAL contention) is what
    # sharding genuinely overlaps, exactly as a pipeline overlaps waiting
    store_ms = 20.0
    fault_env = {
        "SWEED_FAULTPOINTS": f"filer.meta.create=delay:{store_ms / 1e3}::0",
    }
    # the tree lives where the S3 gateway can see it (/buckets/<bucket>);
    # depth 3 makes /buckets/bench/dNN the shard key, so the 16 dirs
    # spread over the fleet — exported here so the parent-side ring
    # clients AND the spawned filers (env-inherited) agree on the split
    os.environ["SWEED_RING_DEPTH"] = "3"
    root = "/buckets/bench"
    paths = [f"{root}/d{i % 16:02d}/f{i:05d}.txt" for i in range(n_files)]
    shuffled = list(paths)
    random.Random(7).shuffle(shuffled)

    def run_fleet(n_filers):
        procs = []
        with tempfile.TemporaryDirectory() as tmp:
            try:
                mp = free_port()
                procs.append(spawn(
                    "import time\n"
                    "from seaweedfs_tpu.server.master_server import MasterServer\n"
                    f"MasterServer(host='127.0.0.1', port={mp}).start()\n"
                    "time.sleep(3600)\n"
                ))
                fports = [free_port() for _ in range(n_filers)]
                ring = [f"127.0.0.1:{p}" for p in fports]
                wait_port(mp)
                for i, fp in enumerate(fports):
                    peers = ring if n_filers > 1 else None
                    procs.append(spawn(
                        "import time\n"
                        "from seaweedfs_tpu.server.filer_server import FilerServer\n"
                        f"FilerServer(host='127.0.0.1', port={fp}, "
                        f"master_url='127.0.0.1:{mp}', "
                        f"db_path={os.path.join(tmp, f'filer{i}.db')!r}, "
                        f"ring_peers={peers!r}).start()\n"
                        "time.sleep(3600)\n",
                        extra_env=fault_env,
                    ))
                for fp in fports:
                    wait_port(fp)
                time.sleep(0.5)

                def storm(op):
                    # shared queue: every worker's NEXT request lands on
                    # whatever shard its path hashes to, so the fleet
                    # stays uniformly loaded
                    work = queue.Queue()
                    for p in shuffled:
                        work.put(p)

                    def worker():
                        rc = RingFilerClient(ring)
                        while True:
                            try:
                                p = work.get_nowait()
                            except queue.Empty:
                                return
                            op(rc, p)

                    with concurrent.futures.ThreadPoolExecutor(c) as pool:
                        t0 = time.perf_counter()
                        futs = [pool.submit(worker) for _ in range(c)]
                        for f in futs:
                            f.result()
                        return time.perf_counter() - t0

                now = int(time.time())
                create_s = storm(lambda rc, p: rc.create_entry(p, {
                    "full_path": p, "is_directory": False,
                    "mtime": now, "chunks": [],
                }))

                def lookup(rc, p):
                    if rc.get_entry(p) is None:
                        raise RuntimeError(f"lookup miss: {p}")

                lookup_s = storm(lookup)

                # -- identical through every gateway shape ----------------
                def gateway_tree(client):
                    # the DUMB surface: follows 307s to shard owners,
                    # spine listings fan out + merge server-side
                    out, stack = {}, [root]
                    while stack:
                        d = stack.pop()
                        for e in client.list(d, limit=10_000):
                            p = f"{d}/{e['name']}"
                            if e.get("is_directory"):
                                stack.append(p)
                            else:
                                out[p] = json.dumps(
                                    e.get("chunks", []), sort_keys=True)
                    return out

                want = gateway_tree(RingFilerClient(ring))
                assert len(want) == n_files, (len(want), n_files)
                gateways_ok = all(
                    gateway_tree(FilerClient(m)) == want for m in ring
                )
                sp = free_port()
                procs.append(spawn(
                    "import time\n"
                    "from seaweedfs_tpu.s3api import S3ApiServer\n"
                    f"S3ApiServer(port={sp}, "
                    f"filer_url={','.join(ring)!r}).start()\n"
                    "time.sleep(3600)\n"
                ))
                wait_port(sp)
                keys = set()
                token = ""
                while True:  # ListObjectsV2 pages through the ring client
                    url = (f"http://127.0.0.1:{sp}/bench?list-type=2"
                           f"&max-keys=1000{token}")
                    with urllib.request.urlopen(url, timeout=20) as r:
                        xml = r.read().decode()
                    import re
                    keys.update(re.findall(r"<Key>([^<]+)</Key>", xml))
                    m = re.search(
                        r"<NextContinuationToken>([^<]+)"
                        r"</NextContinuationToken>", xml)
                    if not m:
                        break
                    token = "&continuation-token=" + urllib.parse.quote(
                        m.group(1))
                s3_ok = keys == {p[len(root) + 1:] for p in paths}
                return {
                    "filers": n_filers,
                    "creates_per_s": round(n_files / create_s, 1),
                    "lookups_per_s": round(n_files / lookup_s, 1),
                    "gateways_identical": bool(gateways_ok),
                    "s3_keys_match": bool(s3_ok),
                }
            finally:
                for p in procs:
                    p.terminate()
                for p in procs:
                    try:
                        p.wait(timeout=10)
                    except subprocess.TimeoutExpired:
                        p.kill()

    one = run_fleet(1)
    four = run_fleet(4)
    print(json.dumps({
        "n_files": n_files,
        "concurrency": c,
        "modeled_store_ms": store_ms,
        "host_cores": os.cpu_count(),
        "note": (
            "creates are the scaling metric (the modeled store wait is "
            "what sharding overlaps); lookups are unmodeled and "
            "client/CPU-bound on a small rig"
        ),
        "fleet_1": one,
        "fleet_4": four,
        "create_scaling_x": round(
            four["creates_per_s"] / max(one["creates_per_s"], 0.1), 2),
        "lookup_scaling_x": round(
            four["lookups_per_s"] / max(one["lookups_per_s"], 0.1), 2),
    }))

class _NullSink:
    """File-like that discards writes: isolates read+H2D+compute+D2H from
    any filesystem at all (the 'where is the first real bottleneck' probe)."""

    def write(self, b):
        return len(b)

    def seek(self, off, whence=0):
        return 0

    def truncate(self, size=None):
        return 0

    def close(self):
        pass


def probe_e2e(dat_mb: int, sink: str = "disk") -> None:
    """Child mode: end-to-end .dat→14-shard-files encode through the overlap
    pipeline (write_ec_files), the path `/admin/ec/generate` runs. Prints one
    line: 'gbps efficiency read_s compute_s write_s'.

    sink: 'disk' (tempdir on this host's disk), 'tmpfs' (/dev/shm — removes
    the disk from both ends), or 'null' (shard writes discarded — pure
    read+device path). NOTE: on this tunneled dev setup the host↔device link
    is ~100 MB/s, so even 'null' measures the tunnel, not a real v5e host's
    PCIe — each mode is labelled accordingly in the BENCH output."""
    import tempfile

    import numpy as np

    from seaweedfs_tpu.ec import encoder
    from seaweedfs_tpu.ec.codec import TpuCodec

    codec = TpuCodec()
    n = dat_mb * 1024 * 1024
    parent = "/dev/shm" if sink in ("tmpfs", "null") else None
    with tempfile.TemporaryDirectory(dir=parent) as tmp:
        base = os.path.join(tmp, "1")
        rng = np.random.default_rng(0)
        with open(base + ".dat", "wb") as f:
            f.write(rng.integers(0, 256, n, dtype=np.uint8).tobytes())
        # the same work plan write_ec_files will compute internally —
        # shared planner, so the warm list below cannot drift from the
        # timed run's actual item widths
        k = codec.data_shards
        chunk, items = encoder.plan_encode(codec, n)
        # warm every kernel shape the timed run will launch: Mosaic
        # compiles per column width, and one compile inside the timed
        # region would swamp the measurement
        align = codec.alignment()
        for w in sorted({encoder._item_width(it) for it in items}):
            pw = align * -(-w // align)
            codec.matmul_device(
                codec.parity_rows,
                codec.device_put(np.ones((k, pw), dtype=np.uint8)),
            ).block_until_ready()
        stats: dict = {}
        t0 = time.perf_counter()
        if sink == "null":
            # same items + pipeline as write_ec_files, shard bytes discarded
            outputs = [_NullSink() for _ in range(codec.total_shards)]
            encoder._encode_pipelined(
                base + ".dat", items, codec, outputs, n, stats=stats
            )
        else:
            # the exact plan the warm loop used — the timed run must launch
            # only warmed kernel shapes, so no internal re-derivation
            encoder.write_ec_files(
                base, codec, plan=(chunk, items), pipeline_stats=stats
            )
        dt = time.perf_counter() - t0
        log(
            f"overlap pipeline [{sink}]: wall={stats['wall_s']:.2f}s "
            f"read={stats['read_busy_s']:.2f}s "
            f"compute={stats['compute_busy_s']:.2f}s "
            f"fetch={stats['fetch_busy_s']:.2f}s "
            f"write={stats['write_busy_s']:.2f}s "
            f"efficiency={stats['efficiency']:.2f} "
            f"(1.0 = wall==max(stage); serial loop would be "
            f"{(stats['read_busy_s'] + stats['compute_busy_s'] + stats['fetch_busy_s'] + stats['write_busy_s']) / stats['wall_s']:.2f}x slower)"
        )
    print(
        f"{n / dt / 1e9:.4f} {stats['efficiency']:.3f} "
        f"{stats['read_busy_s']:.3f} {stats['compute_busy_s']:.3f} "
        f"{stats['fetch_busy_s']:.3f} {stats['write_busy_s']:.3f}"
    )


def probe_extras(sweep_guard_s: float = 240.0) -> None:
    """Child mode: the remaining BASELINE.md bench configs in one cheap
    subprocess — CPU-path 1 GB encode, alt geometries RS(6,3)/RS(12,4) on
    the device, and the 1-missing-data-shard reconstruct p50. Prints one
    JSON line."""
    out = {}

    # CPU path: the C++ fallback encoding 1 GB (the non-TPU rate). The lib
    # is force-rebuilt for THIS host BEFORE anything dlopens it (importing
    # seaweedfs_tpu.native runs ctypes.CDLL at module scope — rebuilding
    # after would measure the stale mapping), and the compiled kernel
    # variant is recorded alongside the rate, so the artifact is
    # self-explaining — r4 published 0.028 GB/s with no way to tell a
    # stale .so from a no-AVX2 host from transient pressure. Best-of-3
    # guards the latter.
    import importlib.util

    spec = importlib.util.find_spec("seaweedfs_tpu.native")
    ndir = os.path.dirname(os.path.abspath(spec.origin))
    try:
        subprocess.run(
            ["make", "-C", ndir, "-s", "-B", "build/_sweed_native.so"],
            check=True, capture_output=True, timeout=120,
        )
    except Exception as e:  # noqa: BLE001 — record, don't die
        out["cpu_rebuild_error"] = str(e)[:200]

    import jax
    import jax.numpy as jnp
    import numpy as np

    from seaweedfs_tpu.ec.codec import CpuCodec, TpuCodec

    cpu = CpuCodec()
    out["cpu_kernel"] = cpu._lib.kernel_variant()
    giga = np.random.default_rng(0).integers(
        0, 256, (10, 100 * 1024 * 1024), dtype=np.uint8
    )
    cpu.encode(giga[:, : 1024 * 1024])  # warm
    # sustained = reused parity buffer, the streaming-encoder scenario
    # (encoder.py passes out= per chunk; klauspost's Go benchmarks likewise
    # reuse the shard slices) — allocating 400 MB of parity per call costs
    # mmap + first-touch page faults comparable to the GFNI kernel itself
    parity_buf = np.empty((cpu.parity_shards, giga.shape[1]), dtype=np.uint8)
    runs = []
    for _ in range(3):
        t0 = time.perf_counter()
        cpu.encode(giga, out=parity_buf)
        runs.append(1.0 * giga.size / (time.perf_counter() - t0) / 1e9)
    out["cpu_encode_gbps"] = round(max(runs), 3)
    out["cpu_encode_runs_gbps"] = [round(r, 3) for r in runs]
    del parity_buf
    t0 = time.perf_counter()
    cpu.encode(giga)
    out["cpu_encode_fresh_gbps"] = round(
        1.0 * giga.size / (time.perf_counter() - t0) / 1e9, 3
    )
    # before/after: the same kernel WITHOUT the cached prep blob — the
    # multiply tables are re-derived inside the call, which is the exact
    # r05 code path — published next to the r05 baseline so the artifact
    # shows what the prep cache + GFNI tier bought without digging through
    # old BENCH files
    matrix = np.ascontiguousarray(cpu.parity_rows, dtype=np.uint8)
    t0 = time.perf_counter()
    cpu._lib.rs_matmul(matrix, giga)
    out["cpu_encode_noprep_gbps"] = round(
        1.0 * giga.size / (time.perf_counter() - t0) / 1e9, 3
    )
    out["cpu_encode_r05_baseline_gbps"] = 1.33  # BENCH_r05 published rate
    out["cpu_encode_vs_r05"] = round(out["cpu_encode_gbps"] / 1.33, 2)
    del giga

    @jax.jit
    def checksum(x):
        return jnp.sum(x, dtype=jnp.uint32)

    # alt geometries on the device (chained ops, ONE host sync per chain —
    # per-op syncs would measure the tunnel). Tile is SWEPT like the main
    # RS(10,4) probe: r4 pinned these to 32KB and published RS(6,3) well
    # below the range the README claimed; the sweep finds each geometry's
    # own best tile, bounded by a wall-clock guard (compiles dominate).
    # Warm-first: a pinned tile in the sidecar (see _tile_cache_path)
    # collapses the sweep to that single tile — the ~50% run-to-run swing
    # on these geometries was the guard truncating the sweep at a
    # different tile each run, not kernel variance.
    t_extras = time.perf_counter()
    n = 32 * 1024 * 1024
    # historically-best tile FIRST per geometry (r5 probes: RS(6,3) peaked
    # at 64KB — 88.6 vs 59.3 GB/s at 32KB; RS(12,4) at 32KB) so the
    # wall-clock guard stopping the sweep early still keeps the best config
    tile_order = {(6, 3): (64, 32, 128, 16), (12, 4): (32, 64, 16, 128)}
    dev_kind = jax.devices()[0].device_kind
    tile_cache = _tile_cache_load()
    for (k, m), tiles in tile_order.items():
        cache_key = f"rs{k},{m}:{dev_kind}"
        pin = tile_cache.get(cache_key, {}).get("tile_kb")
        pinned = pin in tiles
        if pinned:
            tiles = (pin,)
        # one input buffer per geometry (tile-invariant): regenerating it
        # per tile would waste the sweep's own wall budget, and a stale
        # reference pinned by the run closure would keep two resident
        buf = jax.random.bits(jax.random.PRNGKey(k), (k, n), dtype=jnp.uint8)
        buf.block_until_ready()
        best_g, best_tile = 0.0, None
        for tile_kb in tiles:
            if best_tile is not None \
                    and time.perf_counter() - t_extras > sweep_guard_s:
                break
            codec = TpuCodec(k, m, pallas_tile=tile_kb * 1024)
            _ = int(checksum(codec.matmul_device(codec.parity_rows, buf)))

            def run(iters, codec=codec, buf=buf):
                acc = None
                for _ in range(iters):
                    s = checksum(codec.matmul_device(codec.parity_rows, buf))
                    acc = s if acc is None else acc + s
                _ = int(acc)

            sustained, _raw = _sustained_rate(run, k * n, short=8, long_=40)
            del run  # drop the closure so buf has one owner again
            if sustained > best_g:
                best_g, best_tile = sustained, tile_kb
        del buf
        out[f"rs{k}{m}_encode_gbps"] = round(best_g, 2)
        out[f"rs{k}{m}_tile_kb"] = best_tile
        out[f"rs{k}{m}_tile_pinned"] = pinned
        if best_tile is not None and not pinned:
            _tile_cache_store(cache_key, {
                "tile_kb": best_tile,
                "gbps": round(best_g, 2),
                "device": dev_kind,
            })

    # 1-missing-data-shard reconstruct (the common degraded-read case —
    # decode is a (1 × 10) matmul instead of the 4-row worst case); big
    # width so the single host sync doesn't dominate
    codec = TpuCodec(pallas_tile=32 * 1024)
    present_rows = list(range(1, 11))  # shard 0 lost
    decode = codec._decode_matrix_for(present_rows)[:1]
    gen_w = 32 * 1024 * 1024
    buf = None
    # the shared chip's free HBM varies: fall back to narrower widths
    # rather than dying RESOURCE_EXHAUSTED with the whole extras JSON
    # unprinted (this is the last section)
    last_err = ""
    for n in (128 * 1024 * 1024, 64 * 1024 * 1024, 32 * 1024 * 1024):
        pieces = None
        try:
            pieces = [
                jax.random.bits(jax.random.PRNGKey(100 + i),
                                (10, min(gen_w, n - off)), dtype=jnp.uint8)
                for i, off in enumerate(range(0, n, gen_w))
            ]
            buf = jnp.concatenate(pieces, axis=1)
            buf.block_until_ready()
            _ = int(checksum(codec.matmul_device(decode, buf)))
            break
        except Exception as e:  # noqa: BLE001 — RESOURCE_EXHAUSTED et al.
            buf = None
            last_err = str(e)[:200]  # a non-OOM bug must stay visible
        finally:
            del pieces  # drop the failed width's arrays BEFORE retrying
    if buf is None:
        out["reconstruct1_error"] = last_err or "unknown"
        print(json.dumps(out))
        return
    out["reconstruct1_width_mb"] = n // (1024 * 1024)
    times = []
    for _ in range(9):
        t0 = time.perf_counter()
        _ = int(checksum(codec.matmul_device(decode, buf)))
        times.append(time.perf_counter() - t0)
    p50 = sorted(times)[len(times) // 2]
    # p50 is the honest single-call latency (incl. one host sync); the GB/s
    # figure comes from chained ops so the tunnel's fixed per-op round trip
    # doesn't masquerade as kernel cost (same method as every other probe)
    out["reconstruct1_p50_s"] = round(p50, 4)

    def run1(iters):
        acc = None
        for _ in range(iters):
            s = checksum(codec.matmul_device(decode, buf))
            acc = s if acc is None else acc + s
        _ = int(acc)

    # same chain lengths as the geometry sweep above (8/40): the r5 runs
    # with short=4/long=16 scattered 30-51 GB/s on identical code — the
    # fixed-sync cancellation needs more ops to converge at this op size
    sustained, _raw = _sustained_rate(run1, 10 * n, short=8, long_=40)
    out["reconstruct1_gbps"] = round(sustained, 2)
    # the rate trails encode because a 1-missing decode has 8 output bit
    # rows vs encode's 32 on the 128-row MXU tile — skinny-output
    # utilization, not a dispatch fallback (the fused kernel runs here)
    print(json.dumps(out))


def probe_roofline(n_mb: int = 256, guard_s: float = 240.0) -> None:
    """Child mode: the memory-bandwidth roofline behind the encode plateau.

    Two measurements, one JSON line:

    * ``stream_copy_gbps`` — a jitted uint8 ``x + 1`` chained through an
      ``n_mb`` buffer (each link reads + writes every byte, data dependence
      prevents elision). That is the STREAM-style practical HBM ceiling
      this runtime reaches — no arithmetic to hide behind, so no kernel
      can legitimately move bytes faster.
    * ``tiles[]`` — achieved RS(10,4) GF-matmul HBM traffic (read k·n,
      write m·n per op; the per-op checksum's extra parity read is NOT
      counted, so the fraction is conservative) at several tile sizes,
      each as a fraction of the copy ceiling.

    Interpretation: the ~75 GB/s input-rate encode plateau is
    memory-bound iff the best tile's ``roofline_frac`` sits near 1.0 —
    then no tile/kernel tweak moves the headline, only bandwidth does. A
    tile whose fraction falls off is kernel-bound at that shape (VMEM
    re-streaming), which is tuning headroom, not a hardware wall.
    """
    import jax
    import jax.numpy as jnp

    from seaweedfs_tpu.ec.codec import TpuCodec

    t_start = time.perf_counter()
    width = 32 * 1024 * 1024
    chain = (8, 40)
    if jax.default_backend() == "cpu":
        # host-memory roofline is still meaningful, but CPU XLA runs the
        # bit-matmul ~100x slower — shrink so the probe fits its timeout
        n_mb = min(n_mb, 64)
        width = 4 * 1024 * 1024
        chain = (2, 8)
    out = {"buffer_mb": n_mb, "device": jax.devices()[0].device_kind}

    @jax.jit
    def checksum(x):
        return jnp.sum(x, dtype=jnp.uint32)

    @jax.jit
    def stream(x):
        return x + jnp.uint8(1)

    n = n_mb * 1024 * 1024
    buf = jax.random.bits(jax.random.PRNGKey(0), (n,), dtype=jnp.uint8)
    buf.block_until_ready()
    stream(buf).block_until_ready()  # warm/compile

    def run_copy(iters):
        y = buf
        for _ in range(iters):
            y = stream(y)
        _ = int(checksum(y))

    ceiling, raw = _sustained_rate(
        run_copy, 2 * n, short=chain[0], long_=chain[1]
    )
    out["stream_copy_gbps"] = round(ceiling, 2)
    out["stream_copy_raw_gbps"] = round(raw, 2)
    del buf

    k_, m_ = 10, 4
    data = jax.random.bits(jax.random.PRNGKey(1), (k_, width), dtype=jnp.uint8)
    data.block_until_ready()
    tiles_out = []
    for tile_kb in (8, 16, 32, 64, 128):
        if tiles_out and time.perf_counter() - t_start > guard_s:
            out["truncated_at_tile_kb"] = tile_kb  # no silent caps
            break
        try:
            codec = TpuCodec(pallas_tile=tile_kb * 1024)
            _ = int(checksum(codec.matmul_device(codec.parity_rows, data)))
        except Exception as e:  # noqa: BLE001 — tile too big for VMEM etc.
            tiles_out.append({"tile_kb": tile_kb, "error": str(e)[:120]})
            continue

        def run(iters, codec=codec):
            acc = None
            for _ in range(iters):
                s = checksum(codec.matmul_device(codec.parity_rows, data))
                acc = s if acc is None else acc + s
            _ = int(acc)

        enc, _r = _sustained_rate(
            run, k_ * width, short=chain[0], long_=chain[1]
        )
        del run
        hbm = enc * (k_ + m_) / k_
        entry = {"tile_kb": tile_kb, "encode_gbps": round(enc, 2),
                 "hbm_gbps": round(hbm, 2)}
        if ceiling > 0:
            entry["roofline_frac"] = round(hbm / ceiling, 3)
        tiles_out.append(entry)
    out["tiles"] = tiles_out
    print(json.dumps(out))


def probe_query(size_mb: int = 256) -> None:
    """Child mode: vectorized S3-Select scan (query/scan.py) vs the
    pure-Python row-at-a-time engine on a >=size_mb CSV. Prints one JSON
    line with per-backend times, speedups, and a byte-identity verdict.

    Runs on CPU XLA regardless of the parent's device: the scan kernels
    are host-side and gather-heavy, and staging the whole CSV through
    this dev host's ~100 MB/s tunnel every repetition would measure the
    tunnel, not the kernels (same reasoning as the encode probes' on-
    device generation, inverted).

    Warm-up runs the FULL input once per backend before timing: the jit
    backend compiles one kernel per pow2 row-batch bucket, and a warm
    pass that misses a bucket leaves its compile inside the measured run
    (observed as an apparent 2x regression during development).
    """
    os.environ["JAX_PLATFORMS"] = "cpu"
    from seaweedfs_tpu.query import engine
    from seaweedfs_tpu.query.scan import ScanPlan

    # ~26 MB block of distinct rows, repeated to reach size_mb: row text
    # varies within a block (the kernels have no caching to defeat, so
    # block repetition only saves generation time)
    regions = ("east", "west", "north", "south")
    lines = [f"{i},{regions[i & 3]},{i % 1000},r{i:07d}"
             for i in range(1 << 20)]
    body = ("\n".join(lines) + "\n").encode()
    reps = max(1, -(-size_mb * 1024 * 1024 // len(body)))
    data = b"id,region,score,name\n" + body * reps
    del lines, body

    select = ["id", "name"]
    where = {"and": [
        {"field": "region", "op": "=", "value": "east"},
        {"field": "score", "op": ">", "value": 995},
    ]}
    out = {"size_mb": round(len(data) / 1e6, 1)}

    # pure-Python baseline: one run (it IS the slow case being replaced;
    # repeating a minutes-scale scan buys no precision worth the wall)
    t0 = time.perf_counter()
    base = engine.run_query(data, "csv", select=select, where=where)
    out["engine_s"] = round(time.perf_counter() - t0, 2)
    out["rows_matched"] = len(base)

    # 4 MiB chunks — the shape the filer's prefetching chunk stream
    # actually delivers, and measurably faster than one giant buffer
    # (the structural-index intermediates stay cache-sized)
    def chunks():
        for i in range(0, len(data), 4 << 20):
            yield data[i:i + (4 << 20)]

    for label, backend in (("numpy", "numpy"), ("jax", "cpu")):
        try:
            plan = ScanPlan(select=select, where=where,
                            input_format="csv", backend=backend)
        except Exception as e:  # noqa: BLE001 — record, keep the rest
            out[f"{label}_error"] = str(e)[:200]
            continue
        # warm: full input, so every pow2 row-batch bucket (including the
        # final partial batch's) is compiled before the timed runs
        rows = [r for b in plan.scan_iter(chunks()) for r in b]
        times = []
        for _ in range(3):
            t0 = time.perf_counter()
            rows = [r for b in plan.scan_iter(chunks()) for r in b]
            times.append(time.perf_counter() - t0)
        best = min(times)
        out[f"{label}_s"] = round(best, 3)
        out[f"{label}_mbps"] = round(len(data) / best / 1e6, 1)
        out[f"{label}_speedup"] = round(out["engine_s"] / best, 1)
        out[f"{label}_identical"] = rows == base
        out[f"{label}_backend"] = plan.kernels.name
    print(json.dumps(out))


def _run_probe(args: list[str], timeout: int = 420):
    cmd = [sys.executable, os.path.abspath(__file__)] + args
    return subprocess.run(
        cmd, capture_output=True, text=True, timeout=timeout,
        cwd=os.path.dirname(os.path.abspath(__file__)),
    )


def main() -> None:
    import numpy as np

    t_setup = time.perf_counter()

    # -- correctness gate (subprocess-free, small shapes) ---------------------
    from seaweedfs_tpu.ec.codec import CpuCodec, TpuCodec

    cpu = CpuCodec()
    tpu_small = TpuCodec(chunk_bytes=8 * 65536, tile_bytes=65536, pallas_tile=65536)
    rng = np.random.default_rng(0)
    gate = rng.integers(0, 256, (10, 3 * 65536 + 777), dtype=np.uint8)
    if not np.array_equal(cpu.encode(gate), tpu_small.encode(gate)):
        print(
            json.dumps(
                {
                    "metric": "ec.encode",
                    "value": 0.0,
                    "unit": "GB/s/chip",
                    "vs_baseline": 0.0,
                    "error": "bit-identity check FAILED",
                }
            )
        )
        return
    log("bit-identity vs C++ oracle: OK")

    import jax

    dev = jax.devices()[0]
    log(f"device: {dev.device_kind} ({dev.platform})")

    # -- small-file data plane (the reference's weed benchmark workload) ------
    smallfile = None
    try:
        r = _run_probe(["--probe-smallfile", "10000", "16"], timeout=300)
        if r.returncode == 0 and r.stdout.strip():
            smallfile = json.loads(r.stdout.strip().splitlines()[-1])
            smallfile["note"] = (
                "1KB files, c=16, client+servers share this host's core(s); "
                "reference baseline: 15,708 w/s, 47,019 r/s on a MacBook i7 "
                "(README.md:504-538)"
            )
            log(
                f"smallfile: write {smallfile['write']['rps']} req/s "
                f"p50={smallfile['write']['p50_ms']}ms; read "
                f"{smallfile['read']['rps']} req/s "
                f"p50={smallfile['read']['p50_ms']}ms (turbo={smallfile['turbo']})"
            )
        else:
            tail = (r.stderr or "").strip().splitlines()[-1:] or [""]
            log(f"smallfile probe failed: {tail[0][:140]}")
        # full reference scale: the exact workload behind BASELINE.md's
        # 15,708 w/s / 47,019 r/s (benchmark.go:71-75 defaults, n=1048576).
        # The quick n=10k run above keeps signal on constrained hosts; the
        # full run is attempted whenever the quick run passed and the time
        # budget allows (~45-60s of actual pump wall at measured rates).
        # measured ~61s wall on this host (write+read phases ~45s); gate on
        # the PROJECTED duration from the quick run's measured rates, so a
        # constrained host doesn't burn the full subprocess timeout
        projected_s = (
            1048576 / max(smallfile["write"]["rps"], 1)
            + 1048576 / max(smallfile["read"]["rps"], 1)
            if smallfile else float("inf")
        )
        if smallfile and projected_s < 600 \
                and time.perf_counter() - t_setup < 1500:
            rf = _run_probe(["--probe-smallfile", "1048576", "16"],
                            timeout=900)
            if rf.returncode == 0 and rf.stdout.strip():
                full = json.loads(rf.stdout.strip().splitlines()[-1])
                full["note"] = (
                    "FULL reference scale: 1,048,576 × 1KB files, c=16 "
                    "(benchmark.go defaults); baseline 15,708 w/s / "
                    "47,019 r/s"
                )
                smallfile["full_scale"] = full
                log(
                    f"smallfile FULL n=1048576: write "
                    f"{full['write']['rps']} req/s (failed "
                    f"{full['write']['failed']}); read {full['read']['rps']} "
                    f"req/s (failed {full['read']['failed']})"
                )
            else:
                tailf = (rf.stderr or "").strip().splitlines()[-1:] or [""]
                log(f"smallfile full-scale run failed: {tailf[0][:140]}")
    except subprocess.TimeoutExpired:
        log("smallfile probe timed out")

    # -- filer data-plane pipeline (large-file PUT/GET, window sweep) ---------
    # window=1 is the serial pre-pipeline data plane; window=4 overlaps
    # chunk fetches on GET and chunk uploads on PUT (util/pipeline.py)
    filer_pipe = {}
    for w in (1, 4):
        try:
            r = _run_probe(["--probe-filer-pipe", "128", str(w), "2"],
                           timeout=300)
            if r.returncode == 0 and r.stdout.strip():
                filer_pipe[f"window_{w}"] = json.loads(
                    r.stdout.strip().splitlines()[-1]
                )
                fp = filer_pipe[f"window_{w}"]
                log(
                    f"filer_pipe window={w}: PUT {fp['put_gbps']:.3f} GB/s, "
                    f"GET {fp['get_gbps']:.3f} GB/s "
                    f"(128MB, 2MB chunks, {fp['modeled_rtt_ms']:.0f}ms "
                    f"modeled volume latency, identical={fp['identical']})"
                )
            else:
                tail = (r.stderr or "").strip().splitlines()[-1:] or [""]
                log(f"filer_pipe probe window={w} failed: {tail[0][:140]}")
        except subprocess.TimeoutExpired:
            log(f"filer_pipe probe window={w} timed out")
    if len(filer_pipe) == 2:
        w1, w4 = filer_pipe["window_1"], filer_pipe["window_4"]
        filer_pipe["speedup"] = {
            "put": round(w4["put_gbps"] / max(w1["put_gbps"], 1e-9), 2),
            "get": round(w4["get_gbps"] / max(w1["get_gbps"], 1e-9), 2),
            "byte_identical": w1["sha256"] == w4["sha256"]
            and w1["identical"] and w4["identical"],
        }
        log(
            f"filer_pipe speedup window=4 vs 1: "
            f"PUT {filer_pipe['speedup']['put']}x, "
            f"GET {filer_pipe['speedup']['get']}x, "
            f"byte_identical={filer_pipe['speedup']['byte_identical']}"
        )

    # -- serving core: thread-per-connection vs asyncio reactor ---------------
    # same filer smallfile GET workload, keep-alive connection sweep; the
    # reactor's case is the high-connection regime where thread-per-conn
    # burns its wall time on scheduler thrash
    serving = {}
    for mode in ("threads", "aio"):
        try:
            # the qos isolation phase adds ~20s of fixed-duration paced
            # traffic on top of the connection sweep
            r = _run_probe(["--probe-serving", mode, "64,1024", "20000"],
                           timeout=540)
            if r.returncode == 0 and r.stdout.strip():
                serving[mode] = json.loads(r.stdout.strip().splitlines()[-1])
                for row in serving[mode]["sweep"]:
                    s, p = row["sat"], row["paced"]
                    log(
                        f"serving[{mode}] c={row['conns']}: sat "
                        f"{s['rps']} req/s p99={s['p99_ms']}ms "
                        f"failed={s['failed']}; paced {p['rps']} req/s "
                        f"p50={p['p50_ms']}ms p99={p['p99_ms']}ms "
                        f"failed={p['failed']} mismatched={p['mismatched']}"
                    )
                ss = serving[mode].get("serving_state", {})
                qos = serving[mode].get("qos", {})
                log(
                    f"serving[{mode}] native_hits="
                    f"{ss.get('native_hits')} fallbacks="
                    f"{ss.get('native_fallbacks')}; qos compliant p99 "
                    f"solo={qos.get('compliant_solo_p99_ms')}ms vs "
                    f"contended={qos.get('compliant_contended_p99_ms')}ms "
                    f"(greedy shed={qos.get('greedy_shed')}) "
                    f"isolation_ok={qos.get('isolation_ok')}"
                )
            else:
                tail = (r.stderr or "").strip().splitlines()[-1:] or [""]
                log(f"serving probe [{mode}] failed: {tail[0][:140]}")
        except subprocess.TimeoutExpired:
            log(f"serving probe [{mode}] timed out")
    if len(serving) == 2:
        by = {
            (m, row["conns"]): row
            for m in serving for row in serving[m]["sweep"]
        }
        hi = max(c for (_, c) in by)
        lo = min(c for (_, c) in by)
        t, a = by.get(("threads", hi)), by.get(("aio", hi))
        a_lo = by.get(("aio", lo))
        if t and a and a_lo:
            p99_hi = a["paced"]["p99_ms"]
            p99_lo = a_lo["paced"]["p99_ms"]
            serving["aio_vs_threads"] = {
                "conns": hi,
                "sat_rps_ratio": round(
                    a["sat"]["rps"] / max(t["sat"]["rps"], 1e-9), 2
                ),
                "aio_paced_p99_vs_low_conns": round(
                    p99_hi / max(p99_lo, 1e-9), 2
                ) if p99_hi and p99_lo else None,
                "aio_failed": a["sat"]["failed"] + a["paced"]["failed"],
                "aio_mismatched": (
                    a["sat"]["mismatched"] + a["paced"]["mismatched"]
                ),
            }
            log(f"serving aio vs threads @c={hi}: "
                f"{serving['aio_vs_threads']['sat_rps_ratio']}x sat rps; "
                f"aio paced p99 "
                f"{serving['aio_vs_threads']['aio_paced_p99_vs_low_conns']}x "
                f"its c={lo} paced p99")

    # -- tracing tax + the multi-daemon trace tree ---------------------------
    trace_bench = None
    try:
        r = _run_probe(["--probe-trace", "8000", "16"], timeout=420)
        if r.returncode == 0 and r.stdout.strip():
            trace_bench = json.loads(r.stdout.strip().splitlines()[-1])
            put_svcs = (trace_bench.get("put_trace") or {}).get(
                "services", []
            )
            log(
                f"trace: {trace_bench['rps']['traced']} req/s traced vs "
                f"{trace_bench['rps']['untraced']} untraced "
                f"({trace_bench['overhead_pct']}% tax, within 2% budget: "
                f"{trace_bench['within_budget']}); PUT tree spans "
                f"{put_svcs}"
            )
        else:
            tail = (r.stderr or "").strip().splitlines()[-1:] or [""]
            log(f"trace probe failed: {tail[0][:140]}")
    except subprocess.TimeoutExpired:
        log("trace probe timed out")

    # -- hot-shard path: zipfian storm vs heat rebalance + needle cache -------
    hotshard = None
    try:
        r = _run_probe(["--probe-hotshard", "2000000", "40000"], timeout=600)
        if r.returncode == 0 and r.stdout.strip():
            hotshard = json.loads(r.stdout.strip().splitlines()[-1])
            log(
                f"hotshard: baseline p99={hotshard['baseline']['p99_ms']}ms "
                f"→ balanced p99={hotshard['after_balance']['p99_ms']}ms "
                f"→ cached p99={hotshard['after_cache']['p99_ms']}ms "
                f"({hotshard['p99_improvement']}x, hit ratio "
                f"{hotshard['cache_hit_ratio']}, "
                f"mismatched={hotshard['mismatched']})"
            )
        else:
            tail = (r.stderr or "").strip().splitlines()[-1:] or [""]
            log(f"hotshard probe failed: {tail[0][:140]}")
    except subprocess.TimeoutExpired:
        log("hotshard probe timed out")

    # -- active-active replication: lag, outage recovery, dlq drain ----------
    sync_bench = None
    try:
        r = _run_probe(["--probe-sync", "120", "6"], timeout=420)
        if r.returncode == 0 and r.stdout.strip():
            sync_bench = json.loads(r.stdout.strip().splitlines()[-1])
            log(
                f"sync: steady lag p50={sync_bench['steady']['lag_p50_s']}s "
                f"max={sync_bench['steady']['lag_max_s']}s, reconverge "
                f"after {sync_bench['outage_s']}s outage = "
                f"{sync_bench['time_to_converge_s']}s, dlq depth after = "
                f"{sync_bench['totals']['dlq_depth']}, redelivered = "
                f"{sync_bench['totals']['redelivered']}"
            )
        else:
            tail = (r.stderr or "").strip().splitlines()[-1:] or [""]
            log(f"sync probe failed: {tail[0][:140]}")
    except subprocess.TimeoutExpired:
        log("sync probe timed out")

    # -- sharded filer fleet: metadata-plane scale-out -----------------------
    meta_bench = None
    try:
        r = _run_probe(["--probe-meta", "480", "16"], timeout=420)
        if r.returncode == 0 and r.stdout.strip():
            meta_bench = json.loads(r.stdout.strip().splitlines()[-1])
            log(
                f"meta: creates {meta_bench['fleet_1']['creates_per_s']}/s "
                f"(1 filer) -> {meta_bench['fleet_4']['creates_per_s']}/s "
                f"(4 filers) = {meta_bench['create_scaling_x']}x, gateways "
                f"identical={meta_bench['fleet_4']['gateways_identical']}, "
                f"s3 keys match={meta_bench['fleet_4']['s3_keys_match']}"
            )
        else:
            tail = (r.stderr or "").strip().splitlines()[-1:] or [""]
            log(f"meta probe failed: {tail[0][:140]}")
    except subprocess.TimeoutExpired:
        log("meta probe timed out")

    # -- lifecycle autopilot: drifting hot set, live re-tiering --------------
    lifecycle_bench = None
    try:
        r = _run_probe(["--probe-lifecycle", "64", "4000"], timeout=420)
        if r.returncode == 0 and r.stdout.strip():
            lifecycle_bench = json.loads(r.stdout.strip().splitlines()[-1])
            log(
                f"lifecycle: quiesced p99="
                f"{lifecycle_bench['quiesced']['p99_ms']}ms → live p99="
                f"{lifecycle_bench['live']['p99_ms']}ms (ratio "
                f"{lifecycle_bench['p99_ratio']}), tracking "
                f"{lifecycle_bench['tracking']['fraction']} "
                f"(cold moved {lifecycle_bench['tracking']['cold_moved']}/"
                f"{lifecycle_bench['tracking']['cold_total']}, hot local "
                f"{lifecycle_bench['tracking']['hot_still_local']}/"
                f"{lifecycle_bench['tracking']['hot_total']}), s3 bytes "
                f"{lifecycle_bench['tier']['s3_bytes']}, mismatched="
                f"{lifecycle_bench['mismatched']}"
            )
        else:
            tail = (r.stderr or "").strip().splitlines()[-1:] or [""]
            log(f"lifecycle probe failed: {tail[0][:140]}")
    except subprocess.TimeoutExpired:
        log("lifecycle probe timed out")

    # -- encode probes in fresh subprocesses ----------------------------------
    best, best_cfg, best_raw = 0.0, None, 0.0
    successes = 0
    # (32,128) measured up to ~77-88 GB/s in r5 probes (tile sweep beyond
    # 32KB was never tried before); kept second so the best-of-2 early
    # stop compares it against the long-standing (32,16)
    for chunk_mb, tile_kb in ((32, 16), (32, 128), (32, 64), (32, 32),
                              (16, 16), (8, 16)):
        try:
            r = _run_probe(["--probe", str(chunk_mb), str(tile_kb)])
            if r.returncode == 0 and r.stdout.strip():
                parts = r.stdout.strip().splitlines()[-1].split()
                gbps = float(parts[0])
                raw = float(parts[1]) if len(parts) > 1 else gbps
                log(
                    f"encode chunk={chunk_mb}MB tile={tile_kb}KB: "
                    f"{gbps:.2f} GB/s sustained ({raw:.2f} incl. dispatch)"
                )
                successes += 1
                if gbps > best:
                    best, best_cfg, best_raw = gbps, (chunk_mb, tile_kb), raw
            else:
                tail = (r.stderr or "").strip().splitlines()[-1:] or [""]
                log(f"encode chunk={chunk_mb}MB failed: {tail[0][:140]}")
        except subprocess.TimeoutExpired:
            log(f"encode chunk={chunk_mb}MB timed out")
        if successes >= 2 and best >= 8.0:
            break  # enough signal; don't burn bench time

    # -- mesh code path on one chip (certifies multichip inherits the rate) ---
    mesh_gbps = None
    for chunk_mb, tile_kb in ((32, 16), (16, 16)):
        try:
            r = _run_probe(["--probe-mesh", str(chunk_mb), str(tile_kb)],
                           timeout=300)
            if r.returncode == 0 and r.stdout.strip():
                mesh_gbps = float(r.stdout.strip().splitlines()[-1])
                log(
                    f"mesh path (shard_map+fused kernel, 1-device mesh) "
                    f"chunk={chunk_mb}MB tile={tile_kb}KB: {mesh_gbps:.2f} GB/s"
                )
                break
            tail = (r.stderr or "").strip().splitlines()[-1:] or [""]
            log(f"mesh probe chunk={chunk_mb}MB failed: {tail[0][:140]}")
        except subprocess.TimeoutExpired:
            log(f"mesh probe chunk={chunk_mb}MB timed out")

    # -- rebuild probe (4-missing-data-shard worst case) ----------------------
    # matmul_device splits widths beyond chunk_bytes into bounded launches
    # (one huge Mosaic grid used to RESOURCE_EXHAUST past 64MB), so big
    # shards run the same chunked path production uses (rebuild_ec_files);
    # shard sizes below are tried best-of (see the loop comment)
    # the shared chip's load varies: keep the BEST unpipelined rate across
    # shard sizes (retrying the largest once), stopping early once the
    # 8 GB/s bar is cleared; smaller sizes are the low-HBM fallback
    rebuild = None
    # tile sweep for the rebuild shape too: encode's sweep settled on 16KB
    # tiles, and the rebuild 4×10 matmul is the same shape class — r4 only
    # ever ran rebuild at 32KB (VERDICT weak #4)
    for shard_mb, tile_kb in (
        (256, 16), (256, 128), (256, 32), (256, 16), (128, 16), (96, 16),
        (64, 16), (32, 16), (16, 16),
    ):
        if rebuild is not None and time.perf_counter() - t_setup > 900:
            log("rebuild sweep stopped on time budget")
            break
        try:
            r = _run_probe(["--probe-rebuild", str(shard_mb), str(tile_kb)])
            if r.returncode == 0 and r.stdout.strip():
                p50_s, gbps, pipe_gbps = (
                    float(x) for x in r.stdout.strip().split()
                )
                log(
                    f"rebuild shard={shard_mb}MB tile={tile_kb}KB: "
                    f"p50={p50_s*1e3:.1f}ms "
                    f"({gbps:.2f} GB/s; sustained kernel {pipe_gbps:.2f} GB/s)"
                )
                best_pipe = round(pipe_gbps, 2) if rebuild is None else max(
                    rebuild["pipelined_gbps"], round(pipe_gbps, 2)
                )
                if rebuild is None or gbps > rebuild["gbps"]:
                    rebuild = {
                        "p50_s": round(p50_s, 4),
                        "gbps": round(gbps, 2),
                        "pipelined_gbps": round(pipe_gbps, 2),
                        "shard_mb": shard_mb,
                        "tile_kb": tile_kb,
                        "missing": [0, 1, 2, 3],
                    }
                rebuild["pipelined_gbps"] = best_pipe
                if rebuild["gbps"] >= 8.0 and rebuild["pipelined_gbps"] >= 60.0:
                    break
            else:
                tail = (r.stderr or "").strip().splitlines()[-1:] or [""]
                log(f"rebuild shard={shard_mb}MB failed: {tail[0][:140]}")
        except subprocess.TimeoutExpired:
            log(f"rebuild shard={shard_mb}MB timed out")

    # -- MEASURED 30GB-class rebuild: the chunked stream, full 3GB shards -----
    if rebuild is not None:
        for chunk_mb in (32, 16):
            try:
                r = _run_probe(["--probe-rebuild-stream", "3", str(chunk_mb)],
                               timeout=420)
                if r.returncode == 0 and r.stdout.strip():
                    p50_s, gbps, n_chunks = r.stdout.strip().split()
                    rebuild["volume30gb_p50_s_measured"] = float(p50_s)
                    rebuild["volume30gb_stream_gbps"] = float(gbps)
                    rebuild["volume30gb_chunks"] = int(float(n_chunks))
                    log(
                        f"30GB-class rebuild (3GB shards, {chunk_mb}MB chunk "
                        f"stream): p50={float(p50_s):.2f}s ({float(gbps):.2f} GB/s)"
                    )
                    break
                tail = (r.stderr or "").strip().splitlines()[-1:] or [""]
                log(f"rebuild-stream chunk={chunk_mb}MB failed: {tail[0][:140]}")
            except subprocess.TimeoutExpired:
                log(f"rebuild-stream chunk={chunk_mb}MB timed out")

    # -- end-to-end .dat→shard-files probes ------------------------------------
    # three sinks isolate the first real bottleneck: disk (production-shaped,
    # tunnel/disk-bound on this dev host), tmpfs (disk removed from both
    # ends), null (shard writes discarded — pure read+device path)
    e2e = {}
    overlap_eff = None
    for sink in ("disk", "tmpfs", "null"):
        if sink != "disk" and time.perf_counter() - t_setup > 1400:
            log(f"e2e [{sink}] skipped on time budget")
            continue
        try:
            r = _run_probe(["--probe-e2e", "128", sink])
            if r.returncode == 0 and r.stdout.strip():
                parts = r.stdout.strip().splitlines()[-1].split()
                e2e[sink] = {
                    "gbps": float(parts[0]),
                    "efficiency": float(parts[1]),
                    "read_busy_s": float(parts[2]),
                    "compute_busy_s": float(parts[3]),
                    "fetch_busy_s": float(parts[4]),
                    "write_busy_s": float(parts[5]),
                }
                if sink == "disk":
                    overlap_eff = float(parts[1])
                for line in (r.stderr or "").splitlines():
                    if "overlap pipeline" in line:
                        log(line.strip())
                log(
                    f"e2e [{sink}] .dat→14 shard files (128MB): "
                    f"{e2e[sink]['gbps']:.3f} GB/s"
                )
            else:
                tail = (r.stderr or "").strip().splitlines()[-1:] or [""]
                log(f"e2e probe [{sink}] failed: {tail[0][:140]}")
        except subprocess.TimeoutExpired:
            log(f"e2e probe [{sink}] timed out")

    # -- remaining BASELINE.md configs (cpu 1GB, alt geometries, 1-missing) ---
    extras = None
    try:
        # the subprocess's internal sweep guard must sit WELL inside the
        # kill timeout, or a slow host loses the whole extras JSON (it is
        # printed only at the end) — including the CPU numbers computed
        # before the sweep even started
        budget_left = time.perf_counter() - t_setup < 1700
        timeout_s, guard_s = (700, 240) if budget_left else (180, 20)
        r = _run_probe(["--probe-extras", str(guard_s)], timeout=timeout_s)
        if r.returncode == 0 and r.stdout.strip():
            extras = json.loads(r.stdout.strip().splitlines()[-1])
            log(f"extras: {extras}")
        else:
            tail = (r.stderr or "").strip().splitlines()[-1:] or [""]
            log(f"extras probe failed: {tail[0][:140]}")
    except subprocess.TimeoutExpired:
        log("extras probe timed out")

    # -- roofline: streaming-copy HBM ceiling vs GF-matmul bytes/s ------------
    roofline = None
    try:
        r = _run_probe(["--probe-roofline", "256", "240"], timeout=700)
        if r.returncode == 0 and r.stdout.strip():
            roofline = json.loads(r.stdout.strip().splitlines()[-1])
            log(f"roofline: {roofline}")
        else:
            tail = (r.stderr or "").strip().splitlines()[-1:] or [""]
            log(f"roofline probe failed: {tail[0][:140]}")
    except subprocess.TimeoutExpired:
        log("roofline probe timed out")

    # -- query pushdown: vectorized scan vs pure-Python engine (CPU-only) -----
    query_bench = None
    try:
        r = _run_probe(["--probe-query", "256"], timeout=900)
        if r.returncode == 0 and r.stdout.strip():
            query_bench = json.loads(r.stdout.strip().splitlines()[-1])
            log(f"query: {query_bench}")
        else:
            tail = (r.stderr or "").strip().splitlines()[-1:] or [""]
            log(f"query probe failed: {tail[0][:140]}")
    except subprocess.TimeoutExpired:
        log("query probe timed out")

    log(f"best encode: {best:.2f} GB/s at {best_cfg}, total {time.perf_counter() - t_setup:.0f}s")
    print(
        json.dumps(
            {
                "metric": "ec.encode",
                "value": round(best, 2),
                "unit": "GB/s/chip",
                "vs_baseline": round(best / 8.0, 3),
                "baseline": "8 GB/s/chip RS(10,4) target (BASELINE.md)",
                "value_incl_dispatch": round(best_raw, 2),
                "method": (
                    "sustained rate from two chained-op lengths (32 vs 160), "
                    "cancelling the fixed per-chain sync (~100ms through this "
                    "dev tunnel; ~10us on a real v5e host)"
                ),
                "rebuild": rebuild,
                "extras": extras,
                "roofline": roofline,
                "mesh_single_chip_gbps": mesh_gbps,
                "smallfile": smallfile,
                "filer_pipe": filer_pipe,
                "serving": serving,
                "trace": trace_bench,
                "hotshard": hotshard,
                "sync": sync_bench,
                "meta_shard": meta_bench,
                "lifecycle": lifecycle_bench,
                "e2e": e2e,
                "e2e_note": (
                    "all sinks tunnel-bound on this dev host (~100 MB/s "
                    "host<->device link); disk additionally disk-bound"
                ),
                "e2e_disk_gbps_tunnel_bound": (
                    e2e.get("disk", {}).get("gbps")
                ),
                "overlap_efficiency": overlap_eff,
                "query": query_bench,
                "config": {
                    "rs": [10, 4],
                    "kernel": "pallas-fused",
                    "chunk_mb": best_cfg[0] if best_cfg else None,
                    "pallas_tile_kb": best_cfg[1] if best_cfg else None,
                    "device": f"{dev.device_kind}",
                },
            }
        )
    )


if __name__ == "__main__":
    if len(sys.argv) >= 4 and sys.argv[1] == "--probe":
        probe_encode(int(sys.argv[2]), int(sys.argv[3]))
    elif len(sys.argv) >= 4 and sys.argv[1] == "--probe-rebuild":
        probe_rebuild(int(sys.argv[2]), int(sys.argv[3]))
    elif len(sys.argv) >= 4 and sys.argv[1] == "--probe-mesh":
        probe_mesh(int(sys.argv[2]), int(sys.argv[3]))
    elif len(sys.argv) >= 4 and sys.argv[1] == "--probe-rebuild-stream":
        probe_rebuild_stream(int(sys.argv[2]), int(sys.argv[3]))
    elif sys.argv[1:2] == ["--probe-extras"]:
        probe_extras(float(sys.argv[2]) if len(sys.argv) > 2 else 240.0)
    elif sys.argv[1:2] == ["--probe-roofline"]:
        probe_roofline(int(sys.argv[2]) if len(sys.argv) > 2 else 256,
                       float(sys.argv[3]) if len(sys.argv) > 3 else 240.0)
    elif sys.argv[1:2] == ["--probe-query"]:
        probe_query(int(sys.argv[2]) if len(sys.argv) > 2 else 256)
    elif len(sys.argv) >= 4 and sys.argv[1] == "--probe-smallfile":
        probe_smallfile(int(sys.argv[2]), int(sys.argv[3]))
    elif len(sys.argv) >= 4 and sys.argv[1] == "--probe-filer-pipe":
        probe_filer_pipe(int(sys.argv[2]), int(sys.argv[3]),
                         int(sys.argv[4]) if len(sys.argv) > 4 else 4)
    elif len(sys.argv) >= 4 and sys.argv[1] == "--probe-serving":
        probe_serving(sys.argv[2], sys.argv[3],
                      int(sys.argv[4]) if len(sys.argv) > 4 else 20000)
    elif sys.argv[1:2] == ["--probe-trace"]:
        probe_trace(int(sys.argv[2]) if len(sys.argv) > 2 else 8000,
                    int(sys.argv[3]) if len(sys.argv) > 3 else 16)
    elif sys.argv[1:2] == ["--probe-sync"]:
        probe_sync(int(sys.argv[2]) if len(sys.argv) > 2 else 120,
                   float(sys.argv[3]) if len(sys.argv) > 3 else 6.0)
    elif sys.argv[1:2] == ["--probe-lifecycle"]:
        probe_lifecycle(int(sys.argv[2]) if len(sys.argv) > 2 else 64,
                        int(sys.argv[3]) if len(sys.argv) > 3 else 4000)
    elif sys.argv[1:2] == ["--probe-meta"]:
        probe_meta(int(sys.argv[2]) if len(sys.argv) > 2 else 480,
                   int(sys.argv[3]) if len(sys.argv) > 3 else 16)
    elif sys.argv[1:2] == ["--probe-hotshard"]:
        probe_hotshard(
            int(sys.argv[2]) if len(sys.argv) > 2 else 2_000_000,
            int(sys.argv[3]) if len(sys.argv) > 3 else 40_000,
        )
    elif len(sys.argv) >= 3 and sys.argv[1] == "--probe-e2e":
        probe_e2e(int(sys.argv[2]),
                  sys.argv[3] if len(sys.argv) > 3 else "disk")
    else:
        main()
