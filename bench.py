"""Headline benchmark: RS(10,4) ec.encode throughput + 4-missing-shard rebuild p50.

Prints ONE JSON line:
    {"metric": "ec.encode", "value": <GB/s>, "unit": "GB/s/chip",
     "vs_baseline": <value / 8.0>, "rebuild": {...}, ...extras}

Baseline: BASELINE.md north stars — ≥8 GB/s/chip RS(10,4) encode on TPU v5e,
bit-identical to the Go/klauspost path (asserted against the C++ oracle before
timing), and 4-missing-shard rebuild p50 (the reference's `ec.rebuild`
worst case, `weed/storage/erasure_coding/ec_encoder.go:233`).

Method notes:
- Volume bytes are generated on-device: this terminal reaches its TPU through
  a tunnel whose host↔device link is ~100 MB/s (not representative of a real
  v5e host's PCIe). On-device generation isolates the encode kernel, which is
  the component this framework replaces (the klauspost SIMD Encode loop,
  `weed/storage/erasure_coding/ec_encoder.go:179`).
- Each config is probed in a fresh subprocess: the tunneled chip's free HBM
  varies (shared pool), and a RESOURCE_EXHAUSTED poisons the whole device
  session, so in-process retries always fail.
- Each probe runs 3 timed repetitions and reports the best: the shared chip
  shows occasional 4-5× slowdowns from co-tenant activity, and the best-of
  is the stable kernel rate (repeats agree within ~3% when the chip is quiet).
- All diagnostics go to stderr; stdout carries exactly one JSON line.
"""

import json
import os
import subprocess
import sys
import time


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def _timed_reps(run_once, reps: int = 3, iters: int = 6) -> list[float]:
    """Best-of-reps timing loop: returns per-rep seconds/iter."""
    out = []
    for _ in range(reps):
        t0 = time.perf_counter()
        run_once(iters)
        out.append((time.perf_counter() - t0) / iters)
    return out


def probe_encode(chunk_mb: int, tile_kb: int) -> None:
    """Child mode: time encode for one config, print one float (GB/s)."""
    import jax
    import jax.numpy as jnp

    from seaweedfs_tpu.ec.codec import TpuCodec

    codec = TpuCodec(
        chunk_bytes=chunk_mb * 1024 * 1024, pallas_tile=tile_kb * 1024
    )
    n = chunk_mb * 1024 * 1024

    @jax.jit
    def checksum(x):
        return jnp.sum(x, dtype=jnp.uint32)

    data = jax.random.bits(jax.random.PRNGKey(0), (10, n), dtype=jnp.uint8)
    data.block_until_ready()
    p = codec.matmul_device(codec.parity_rows, data)
    _ = int(checksum(p))  # compile + warm

    def run(iters):
        acc = None
        for _ in range(iters):
            s = checksum(codec.matmul_device(codec.parity_rows, data))
            acc = s if acc is None else acc + s
        _ = int(acc)  # forces execution of the whole chain

    dt = min(_timed_reps(run))
    print(f"{10 * n / dt / 1e9:.4f}")


def probe_rebuild(shard_mb: int, tile_kb: int) -> None:
    """Child mode: 4-missing-data-shard rebuild. Prints 'p50_s gbps'.

    Worst case of the reference's `ec.rebuild`: data shards 0-3 lost, rebuilt
    from the 10 remaining (6 data + 4 parity) via the inverted decode matrix
    (`ec_encoder.go:233` rebuildEcFiles → klauspost Reconstruct).
    """
    import jax
    import jax.numpy as jnp

    from seaweedfs_tpu.ec.codec import TpuCodec

    codec = TpuCodec(pallas_tile=tile_kb * 1024)
    n = shard_mb * 1024 * 1024
    present_rows = list(range(4, 14))  # shards 4..13 survive
    decode = codec._decode_matrix_for(present_rows)[:4]  # rows for shards 0-3

    @jax.jit
    def checksum(x):
        return jnp.sum(x, dtype=jnp.uint32)

    present = jax.random.bits(jax.random.PRNGKey(1), (10, n), dtype=jnp.uint8)
    present.block_until_ready()
    rebuilt = codec.matmul_device(decode, present)
    _ = int(checksum(rebuilt))  # compile + warm

    times = []
    for _ in range(9):
        t0 = time.perf_counter()
        rebuilt = codec.matmul_device(decode, present)
        _ = int(checksum(rebuilt))
        times.append(time.perf_counter() - t0)
    p50 = sorted(times)[len(times) // 2]

    # pipelined rate: chain iterations without per-op host sync (the p50 above
    # includes one tunnel round-trip per op, which a real host wouldn't pay)
    def run(iters):
        acc = None
        for _ in range(iters):
            s = checksum(codec.matmul_device(decode, present))
            acc = s if acc is None else acc + s
        _ = int(acc)

    dt = min(_timed_reps(run))
    # GB/s of source bytes processed (10 shards in, 4 rebuilt out)
    print(f"{p50:.6f} {10 * n / p50 / 1e9:.4f} {10 * n / dt / 1e9:.4f}")


def probe_e2e(dat_mb: int) -> None:
    """Child mode: end-to-end disk→14-shard-files encode through the overlap
    pipeline (write_ec_files), the path `/admin/ec/generate` runs. Prints one
    float (GB/s of .dat bytes). NOTE: on this tunneled dev setup the
    host↔device link is ~100 MB/s, so this measures the tunnel, not a real
    v5e host's PCIe — reported as a secondary, honestly-labelled number."""
    import tempfile

    import numpy as np

    from seaweedfs_tpu.ec import encoder
    from seaweedfs_tpu.ec.codec import TpuCodec

    codec = TpuCodec()
    n = dat_mb * 1024 * 1024
    with tempfile.TemporaryDirectory() as tmp:
        base = os.path.join(tmp, "1")
        rng = np.random.default_rng(0)
        with open(base + ".dat", "wb") as f:
            f.write(rng.integers(0, 256, n, dtype=np.uint8).tobytes())
        # small warm chunk to absorb kernel compiles before timing
        warm = os.path.join(tmp, "w")
        with open(warm + ".dat", "wb") as f:
            f.write(b"\x01" * (4 * 1024 * 1024))
        encoder.write_ec_files(warm, codec)
        t0 = time.perf_counter()
        encoder.write_ec_files(base, codec)
        dt = time.perf_counter() - t0
    print(f"{n / dt / 1e9:.4f}")


def _run_probe(args: list[str], timeout: int = 420):
    cmd = [sys.executable, os.path.abspath(__file__)] + args
    return subprocess.run(
        cmd, capture_output=True, text=True, timeout=timeout,
        cwd=os.path.dirname(os.path.abspath(__file__)),
    )


def main() -> None:
    import numpy as np

    t_setup = time.perf_counter()

    # -- correctness gate (subprocess-free, small shapes) ---------------------
    from seaweedfs_tpu.ec.codec import CpuCodec, TpuCodec

    cpu = CpuCodec()
    tpu_small = TpuCodec(chunk_bytes=8 * 65536, tile_bytes=65536, pallas_tile=65536)
    rng = np.random.default_rng(0)
    gate = rng.integers(0, 256, (10, 3 * 65536 + 777), dtype=np.uint8)
    if not np.array_equal(cpu.encode(gate), tpu_small.encode(gate)):
        print(
            json.dumps(
                {
                    "metric": "ec.encode",
                    "value": 0.0,
                    "unit": "GB/s/chip",
                    "vs_baseline": 0.0,
                    "error": "bit-identity check FAILED",
                }
            )
        )
        return
    log("bit-identity vs C++ oracle: OK")

    import jax

    dev = jax.devices()[0]
    log(f"device: {dev.device_kind} ({dev.platform})")

    # -- encode probes in fresh subprocesses ----------------------------------
    best, best_cfg = 0.0, None
    successes = 0
    for chunk_mb, tile_kb in ((32, 32), (32, 16), (16, 32), (8, 16)):
        try:
            r = _run_probe(["--probe", str(chunk_mb), str(tile_kb)])
            if r.returncode == 0 and r.stdout.strip():
                gbps = float(r.stdout.strip().splitlines()[-1])
                log(f"encode chunk={chunk_mb}MB tile={tile_kb}KB: {gbps:.2f} GB/s")
                successes += 1
                if gbps > best:
                    best, best_cfg = gbps, (chunk_mb, tile_kb)
            else:
                tail = (r.stderr or "").strip().splitlines()[-1:] or [""]
                log(f"encode chunk={chunk_mb}MB failed: {tail[0][:140]}")
        except subprocess.TimeoutExpired:
            log(f"encode chunk={chunk_mb}MB timed out")
        if successes >= 2 and best >= 8.0:
            break  # enough signal; don't burn bench time

    # -- rebuild probe (4-missing-data-shard worst case) ----------------------
    rebuild = None
    for shard_mb in (32, 16):
        try:
            r = _run_probe(["--probe-rebuild", str(shard_mb), "32"])
            if r.returncode == 0 and r.stdout.strip():
                p50_s, gbps, pipe_gbps = (
                    float(x) for x in r.stdout.strip().split()
                )
                # extrapolate to a 30GB volume's 3GB shards (linear in bytes,
                # at the pipelined rate — a 3GB rebuild amortizes the sync)
                vol_p50 = p50_s + (3 * 1024 - shard_mb) / shard_mb * (
                    10 * shard_mb / 1024 / pipe_gbps
                )
                rebuild = {
                    "p50_s": round(p50_s, 4),
                    "gbps": round(gbps, 2),
                    "pipelined_gbps": round(pipe_gbps, 2),
                    "shard_mb": shard_mb,
                    "missing": [0, 1, 2, 3],
                    "volume30gb_p50_s_extrapolated": round(vol_p50, 1),
                }
                log(
                    f"rebuild shard={shard_mb}MB: p50={p50_s*1e3:.1f}ms "
                    f"({gbps:.2f} GB/s; pipelined {pipe_gbps:.2f} GB/s)"
                )
                break
            tail = (r.stderr or "").strip().splitlines()[-1:] or [""]
            log(f"rebuild shard={shard_mb}MB failed: {tail[0][:140]}")
        except subprocess.TimeoutExpired:
            log(f"rebuild shard={shard_mb}MB timed out")

    # -- end-to-end disk→shard-files probe (tunnel-bound on this dev setup) ---
    e2e = None
    try:
        r = _run_probe(["--probe-e2e", "128"])
        if r.returncode == 0 and r.stdout.strip():
            e2e = float(r.stdout.strip().splitlines()[-1])
            log(f"e2e disk→14 shard files (128MB .dat): {e2e:.3f} GB/s (tunnel-bound)")
        else:
            tail = (r.stderr or "").strip().splitlines()[-1:] or [""]
            log(f"e2e probe failed: {tail[0][:140]}")
    except subprocess.TimeoutExpired:
        log("e2e probe timed out")

    log(f"best encode: {best:.2f} GB/s at {best_cfg}, total {time.perf_counter() - t_setup:.0f}s")
    print(
        json.dumps(
            {
                "metric": "ec.encode",
                "value": round(best, 2),
                "unit": "GB/s/chip",
                "vs_baseline": round(best / 8.0, 3),
                "baseline": "8 GB/s/chip RS(10,4) target (BASELINE.md)",
                "rebuild": rebuild,
                "e2e_disk_gbps_tunnel_bound": e2e,
                "config": {
                    "rs": [10, 4],
                    "kernel": "pallas-fused",
                    "chunk_mb": best_cfg[0] if best_cfg else None,
                    "pallas_tile_kb": best_cfg[1] if best_cfg else None,
                    "device": f"{dev.device_kind}",
                },
            }
        )
    )


if __name__ == "__main__":
    if len(sys.argv) >= 4 and sys.argv[1] == "--probe":
        probe_encode(int(sys.argv[2]), int(sys.argv[3]))
    elif len(sys.argv) >= 4 and sys.argv[1] == "--probe-rebuild":
        probe_rebuild(int(sys.argv[2]), int(sys.argv[3]))
    elif len(sys.argv) >= 3 and sys.argv[1] == "--probe-e2e":
        probe_e2e(int(sys.argv[2]))
    else:
        main()
